"""Inference engine: jitted prefill/decode over a (dp, tp) mesh.

TPU-native counterpart of the reference's runtime stack (NnExecutor +
RootLlmInference/WorkerLlmInference, src/nn/nn-executor.cpp +
src/app.cpp:170-230): the pthread step-list interpreter and the per-forward
control-packet broadcast collapse into two jit-compiled XLA programs
(prefill at a few bucketed chunk lengths, decode at T=1) with a donated KV
cache. Sampling for the greedy path is fused on-device so the decode loop
ships one int32 per token instead of a [vocab] logits row; the
temperature/top-p path uses the reference-parity host sampler.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..formats.model_file import LlmHeader, ModelReader
from ..formats.quants import FloatType
from ..models import forward, init_kv_cache, load_params
from ..parallel import cache_specs, make_mesh, shard_params_put, validate_tp
from ..tokenizer import Tokenizer
from .faults import get_fault_plane
from .sampler import Sampler

# Prefill chunk buckets: one compiled program per bucket (the reference's
# --nBatches plays the same role: its graphs are compiled-in for nBatches
# rows and prefill walks the prompt in nBatches-sized chunks).
DEFAULT_PREFILL_BUCKETS = (1, 8, 32, 128, 512)


def _sds(x):
    """ShapeDtypeStruct (with sharding) of one live array — the lowering
    spec the AOT pre-compiles consume."""
    return jax.ShapeDtypeStruct(
        x.shape, x.dtype, sharding=getattr(x, "sharding", None)
    )


def _topp_mask(probs, topp):
    """Top-p nucleus mask on device, [B, V] probs -> masked probs; `topp`
    is a scalar or a per-lane [B] vector.

    Same selection rule as the host sampler (apply the cutoff pre-filter
    (1 - topp) / (V - 1), then keep the smallest prefix of descending
    probs whose cumulative mass exceeds topp, including the crossing
    token — reference: sample_topp, tokenizer.cpp:426-467); topp outside
    (0, 1) keeps the full distribution, matching the host sampler's
    sample_mult fallthrough, and a cumsum that never crosses (f32
    rounding at topp near 1) keeps the cutoff-filtered set, matching the
    host's empty-`over` branch (which also samples from the filtered
    set). Split out so its support set can be equivalence-tested against
    the host rule (tests/test_engine.py).
    Known divergence: exact prob TIES at the nucleus boundary keep all
    tied tokens here (threshold rule) where the host keeps only those
    before its sort's crossing point — the host's own tie order is
    sort-dependent, so the boundary choice is arbitrary in both.
    """
    b, v = probs.shape
    topp_col = jnp.broadcast_to(
        jnp.atleast_1d(jnp.asarray(topp, jnp.float32)), (b,)
    )[:, None]
    topp_valid = jnp.logical_and(topp_col > 0.0, topp_col < 1.0)
    # host sampler pre-filter: rows below (1-topp)/(V-1) can never be part
    # of a nucleus that still needs them; the host drops them before its
    # sort and KEEPS ONLY the filtered set in the never-crosses fallback
    cutoff = (1.0 - topp_col) / jnp.float32(v - 1)
    pf = jnp.where(jnp.logical_and(topp_valid, probs < cutoff), 0.0, probs)
    sorted_probs = jnp.sort(pf, axis=-1)[..., ::-1]
    csum = jnp.cumsum(sorted_probs, axis=-1)
    crossed = csum > topp_col
    cross = jnp.where(
        jnp.any(crossed, axis=-1),
        jnp.argmax(crossed, axis=-1),
        v - 1,
    )
    thresh = jnp.take_along_axis(sorted_probs, cross[..., None], axis=-1)
    # never-crosses fallback: thresh is the smallest filtered value (> 0
    # rows kept), so the support is exactly the cutoff-filtered set
    thresh = jnp.maximum(thresh, cutoff)
    masked = jnp.where(pf >= thresh, pf, 0.0)
    return jnp.where(topp_valid, masked, probs)


def _sample_on_device(logits, temperature, topp, key):
    """Temperature + top-p sampling on device, [B, V] f32 -> [B] int32;
    `temperature`/`topp` may be per-lane [B] vectors, and lanes with
    temperature == 0 take the greedy argmax — so one compiled program
    serves any mix of sampling settings across lanes.

    Host-sampler selection rule (see _topp_mask) driven by the JAX PRNG
    instead of xorshift: on-device sampling keeps the decode loop free of
    per-token host round trips. Seeded runs are reproducible, just under a
    different (documented) RNG than the reference.
    """
    b = logits.shape[0]
    temp_col = jnp.broadcast_to(
        jnp.atleast_1d(jnp.asarray(temperature, jnp.float32)), (b,)
    )[:, None]
    probs = _topp_mask(
        jax.nn.softmax(logits / jnp.maximum(temp_col, 1e-6), axis=-1), topp
    )
    sampled = jax.random.categorical(
        key, jnp.log(probs + 1e-30), axis=-1
    ).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temp_col[:, 0] <= 0.0, greedy, sampled)


def _sample_per_lane(logits, temperature, topp, seeds, positions):
    """Per-LANE seeded sampling: lane l's key derives from (seeds[l],
    positions[l]) only, so a seeded request's draws are reproducible
    regardless of which other lanes are active and of how the block
    decode is split (the key depends on the absolute position, not the
    block offset). Greedy lanes (temperature 0) ignore the key."""
    b = logits.shape[0]
    temp_col = jnp.broadcast_to(
        jnp.atleast_1d(jnp.asarray(temperature, jnp.float32)), (b,)
    )[:, None]
    probs = _topp_mask(
        jax.nn.softmax(logits / jnp.maximum(temp_col, 1e-6), axis=-1), topp
    )
    logp = jnp.log(probs + 1e-30)
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
    )(seeds, positions)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(keys, logp).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temp_col[:, 0] <= 0.0, greedy, sampled)


@dataclasses.dataclass
class StepStats:
    """Per-forward timing surface (reference: dllama.cpp:59-66,88-95)."""

    time_ms: float
    n_tokens: int


class InferenceEngine:
    """See module docstring. `batch_size` > 1 turns the batch axis into
    independent decoding lanes (`generate_batch`) — the data-parallel
    throughput surface the reference lacks (SURVEY.md §2 marks DP absent
    there)."""

    def __init__(
        self,
        model_path: str,
        tokenizer: Tokenizer | None = None,
        tp: int = 1,
        dp: int = 1,
        sp: int = 1,
        pp: int = 1,
        dtype=jnp.bfloat16,
        kv_dtype=None,
        max_seq_len: int = 0,
        batch_size: int = 1,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 12345,
        prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
        matmul_precision: str | None = None,
        weight_format: str = "auto",
        buffer_float_type: str = "f32",
        moe_decode_dedup: bool | str = "auto",
    ):
        # observability hooks (obs/metrics.py): every handle below is a
        # no-op when the registry is disabled, so the decode path carries
        # one attribute read of overhead in that state. Created before the
        # first _fresh_cache() call (which bumps the epoch counter).
        from ..obs.metrics import (
            DEFAULT_TOKEN_BUCKETS_S,
            get_registry,
        )
        from ..obs.recorder import get_recorder

        self.obs = get_registry()
        # flight recorder (obs/recorder.py): structured engine events —
        # dispatches, compiles, cache epochs, errors — in a bounded ring;
        # /v1/debug/recorder dumps it, crashes postmortem it
        self.recorder = get_recorder()
        # span timelines (obs/spans.py): every dispatch below brackets a
        # component="engine" span, with a nested ".device" span splitting
        # host dispatch from device completion on the block-decode paths
        from ..obs.spans import get_span_tracker

        self._spans = get_span_tracker()
        self._m_step = self.obs.histogram(
            "dllama_engine_step_seconds",
            "Wall time of one engine dispatch (compiled program call + "
            "host readback), by step kind.",
            labelnames=("kind",),
        )
        self._m_compiles = self.obs.counter(
            "dllama_engine_compiles_total",
            "Compiled-program builds by origin: dispatch = synchronous "
            "compile on the serving path, prefetch = background window "
            "pre-compile, prefetch-failed = a broken prefetch (boundary "
            "will stall on a synchronous compile).",
            labelnames=("origin",),
        )
        self._m_xlalint = self.obs.counter(
            "dllama_xlalint_findings_total",
            "New (non-baselined) xlalint findings on freshly compiled "
            "programs; any increment means a compiled executable broke "
            "a donation/collective/dtype/host/cost-budget invariant.",
        )
        self._m_window_crossings = self.obs.counter(
            "dllama_engine_window_crossings_total",
            "Attention-window boundary crossings (a larger compiled "
            "window took over mid-generation).",
        )
        self._m_epochs = self.obs.counter(
            "dllama_engine_cache_epochs_total",
            "KV-cache rebuilds (engine init, reset, or crash-consistency "
            "recovery after a failed donated dispatch).",
        )
        self._m_tpot = self.obs.histogram(
            "dllama_engine_block_token_seconds",
            "Per-token share of a block decode dispatch (dispatch wall "
            "time / tokens in the block).",
            buckets=DEFAULT_TOKEN_BUCKETS_S,
        )
        self._m_kv_copy_bytes = self.obs.counter(
            "dllama_kv_copy_bytes_total",
            "Device bytes moved by KV copy programs: slab adopt/publish "
            "page copies, plus the pool-native path's COW mid-page tail "
            "forks (its only remaining device copy — a full-page prefix "
            "adoption moves zero bytes).",
        )
        self._obs_last_window = None

        self.reader = ModelReader(model_path, max_seq_len=max_seq_len)
        self.header: LlmHeader = self.reader.header
        self.tokenizer = tokenizer
        validate_tp(self.header, tp)
        # sequence parallelism: the KV cache's sequence axis shards over sp
        # chips (the long-context axis; models/transformer._attention_sp).
        # Shard boundaries must tile the cache.
        if sp < 1 or (sp & (sp - 1)) != 0:
            raise ValueError(f"sp must be a power of two >= 1, got {sp}")
        if sp > 1 and self.header.seq_len % sp != 0:
            raise ValueError(
                f"seqLen {self.header.seq_len} not divisible by sp={sp}"
            )
        # pipeline stages: layer ranges per stage (parallel/pipeline.py) —
        # the capacity axis past the reference's nNodes <= nKvHeads bound.
        # Composes with tp (stages of tp groups), dp (lanes sharded inside
        # stages) and sp (stage-local sequence shards, manual merged-stats
        # attention).
        from ..parallel.pipeline import validate_pp

        validate_pp(self.header, pp)
        if dp > 1 and batch_size % dp != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide over dp={dp} lanes"
            )
        self.mesh = make_mesh(tp=tp, dp=dp, sp=sp, pp=pp)
        self.tp, self.dp, self.sp, self.pp = tp, dp, sp, pp
        self.batch_size = batch_size
        self.dtype = dtype
        # kv_dtype "int8" (or jnp.int8) turns on the quantized KV cache
        # (models/transformer.QuantKV): per-row int8 values + f32 scales,
        # ~2x KV capacity vs bf16 — the long-context fit lever on top of
        # windowed reads (VERDICT r3 item 8)
        if isinstance(kv_dtype, str):
            named = {
                "f32": jnp.float32,
                "f16": jnp.float16,
                "bf16": jnp.bfloat16,
                "int8": jnp.int8,
            }
            if kv_dtype not in named:
                raise ValueError(
                    f"kv_dtype must be one of {sorted(named)}, got "
                    f"{kv_dtype!r}"
                )
            kv_dtype = named[kv_dtype]
        self.kv_dtype = kv_dtype or dtype
        self.sampler = Sampler(self.header.vocab_size, temperature, topp, seed)
        self.temperature = temperature
        self._precision = matmul_precision
        # sp > 1: prefill chunks > 1 token shard their query axis over sp,
        # so buckets must divide evenly (width-1 chunks go through the
        # merged-stats decode branch instead)
        self.prefill_buckets = tuple(
            b
            for b in sorted(prefill_buckets)
            if b <= self.header.seq_len
            and (sp == 1 or b == 1 or b % sp == 0)
            # pp x sp: stage-local sp writes are windowed per shard, so a
            # chunk must fit one shard's local rows (run_layers sp_axis)
            and (pp == 1 or sp == 1 or b <= self.header.seq_len // sp)
        ) or ((1,) if sp == 1 else (sp,))

        # "auto": keep Q40 weights quantized on device when the Pallas path
        # is available (TPU); dense bf16/f32 elsewhere (the CPU fallback
        # dequantizes per call, fine for tests, slow for serving).
        if weight_format == "auto":
            weight_format = (
                "q40"
                if (
                    self.header.weight_type == FloatType.Q40
                    and jax.default_backend() == "tpu"
                )
                else "dense"
            )
        if weight_format not in ("dense", "q40", "q40i8", "q40i4"):
            raise ValueError(
                f"weight_format must be 'auto', 'dense', 'q40', 'q40i8' or "
                f"'q40i4', got {weight_format!r}"
            )
        self.weight_format = weight_format
        quantized = weight_format in ("q40", "q40i8", "q40i4")
        # Q80-compressed partial-sum all-reduces (the reference's
        # --buffer-float-type q80, src/llm.cpp:195): worthwhile on
        # DCN-connected multi-host pods where sync bytes are the
        # bottleneck; over single-host ICI the exact f32 psum is the
        # right default (ICI bandwidth dwarfs the [dim] payload).
        if buffer_float_type not in ("f32", "q80"):
            raise ValueError(
                f"buffer_float_type must be 'f32' or 'q80', got "
                f"{buffer_float_type!r}"
            )
        self._sync_quant = buffer_float_type == "q80"
        if quantized and tp > 1:
            # col-split quant weights shard the scale tensor's block axis
            # (in//32): every contraction dim must divide by 32*tp
            for dim_name, dim in [
                ("dim", self.header.dim),
                ("qDim", self.header.q_dim),
                ("hiddenDim", self.header.ff_dim),
            ]:
                if dim % (32 * tp) != 0:
                    raise ValueError(
                        f"q40 weight format with tp={tp} needs {dim_name} "
                        f"divisible by {32 * tp}, got {dim}"
                    )
        self.params = load_params(
            self.reader,
            dtype=dtype,
            put=shard_params_put(self.mesh, self.header),
            # q40i8 loads the wire's Q40 blocks first, then requantizes;
            # q40i4 packs host-side inside the loader itself
            weight_format="q40" if weight_format == "q40i8" else weight_format,
            # quantized path: fuse q|k|v (and w1|w3 for dense-FFN archs)
            # into single shard-major-interleaved kernel launches — 7 -> 4
            # Pallas calls per decode layer (~41 us fixed cost each,
            # docs/silicon_r03.md)
            fuse=tp if quantized else 0,
        )
        self.i8_group = 0
        if weight_format == "q40i8":
            # grouped-int8 device format: native MXU integer dots instead
            # of per-element VPU dequant (ops/int8_matmul.py) — the r4
            # answer to the Q40 kernel's 46%-of-HBM-peak ceiling
            from ..ops.int8_matmul import pick_group, requantize_params

            self.i8_group = pick_group(self.header, tp)
            self.params = requantize_params(
                self.params, self.header, self.i8_group
            )
        # Per-lane serving: lanes park their cache writes in padding rows
        # beyond seqLen while other lanes prefill/idle, so independent
        # requests can occupy the batch lanes at different positions.
        # Padding must cover the widest chunk a parked lane "writes";
        # under sp it is rounded up so the padded sequence axis still
        # tiles across the sp shards. Pipeline stages reuse the same
        # scratch rows for INVALID-tick writes (parallel/pipeline.py
        # park_pos): without padding every tick select-merges the whole
        # stage cache, which costs as much HBM as the stage weight read.
        pad = max(self.prefill_buckets) if (batch_size > 1 or pp > 1) else 0
        if pad and sp > 1:
            pad += (-pad) % sp
        self._lane_pad = pad
        self._park = self.header.seq_len  # first padding row
        self._cache_sharding = {
            k: NamedSharding(self.mesh, spec)
            for k, spec in cache_specs(
                self.header, sp=sp > 1, pp=pp > 1
            ).items()
        }
        self.cache = self._fresh_cache()
        self._token_sharding = NamedSharding(self.mesh, P("dp", None))
        # AOT lowering specs are SNAPSHOTTED once here (r5 advisor item):
        # params never change after init and every fresh cache has the
        # same shapes/dtypes/shardings, so the prefetch thread lowers
        # against this frozen tree instead of reading `self.cache` live —
        # the live tree's buffers may be donated (deleted) mid-read by a
        # concurrent dispatch on the serving thread.
        self._param_specs = jax.tree.map(_sds, self.params)
        self._cache_specs = jax.tree.map(_sds, self.cache)
        # resident draft model (second-generation speculation): loaded on
        # demand by init_draft_model; None means mode "draft" is off and
        # no draft program ever compiles
        self._draft_params = None
        self._draft_header: LlmHeader | None = None
        self.draft_cache = None
        self.draft_cache_epoch = 0
        self._m_spec_draft_ms = None
        # shared KV page pool (cross-lane prefix sharing): allocated on
        # demand by init_kv_pool; None means the paged path is off
        self.kv_pool = None
        self._kv_page_size = 0
        self._kv_pool_pages = 0
        self._kv_pool_specs = None
        # pool-native mode (ISSUE 16): the pool IS the lane KV home —
        # decode/verify/prefill read and write through a per-lane page
        # table instead of the slab, kv_adopt becomes a page-table write
        # and kv_publish an ownership transfer. kv_pool_epoch moves every
        # time the pool buffer is reallocated so the manager/scheduler can
        # tell "this dispatch poisoned the pool" from a transient failure.
        self.kv_native = False
        self.kv_pool_epoch = 0
        self._kv_n_blocks = 0
        self._page_table = None  # host np.int32 mirror [batch, n_blocks]
        self._compiled = {}
        self._base_key = jax.random.PRNGKey(seed)
        self._lane_seed_base = seed
        self._rng_calls = 0
        # window pre-compile (VERDICT r4 #7): decode blocks are AOT-
        # compiled so a background thread can build the NEXT window's
        # program before a lane crosses the boundary — the crossing then
        # performs no synchronous compile. _compile_origin records who
        # built each program (the boundary-stall test pins "prefetch").
        import os as _os
        import threading as _threading

        from ..analysis.lockwatch import make_lock

        self._aot_blocks = (
            _os.environ.get("DLLAMA_WINDOW_PRECOMPILE", "1") != "0"
        )
        self._compile_lock = make_lock("engine.compile")
        self._inflight: dict = {}  # key -> threading.Event
        self._compile_origin: dict = {}
        self._compile_seconds: dict = {}  # key -> AOT build wall seconds
        # XLA cost analysis memoized per compiled key: the series sampler
        # refreshes the cost gauges at ~1 Hz via the registry's
        # "engine.cost" hook, and cost_analysis() on every program every
        # tick would dwarf the tick itself. "unavailable" (None) results
        # are NOT cached — a lazily jitted program exposes its executable
        # only after its first call.
        self._cost_cache: dict = {}
        self.obs.add_refresh_hook("engine.cost", self.cost_report)
        # compiled-program lint (xlalint, docs/static_analysis.md): every
        # AOT build is checked right after it lands in the cache —
        # donation honored, collective census, dtype/host policy, cost
        # budget. "0"/"off" disables, "strict" raises XlalintError on a
        # new finding (dispatch-path compiles propagate it; prefetch
        # threads log it and mark the key prefetch-failed), anything
        # else warns through the engine logger.
        self._xlalint_mode = (
            _os.environ.get("DLLAMA_XLALINT", "warn").strip().lower()
        )
        self._xlalint_baseline: set | None = None

        if moe_decode_dedup == "auto":
            # decision boundary from the routing-correlation study
            # (scripts/moe_routing_sim.py, docs/moe_decode_dedup.md): at
            # >= 8 decode lanes the small grid hits ~always under even
            # moderate inter-lane correlation (rho 0.5) or mild expert-
            # popularity skew, and a miss just takes the ragged branch;
            # under 8 lanes hits need strong correlation, so the second
            # compiled program isn't worth carrying
            moe_decode_dedup = bool(self.header.n_experts and batch_size >= 8)
        self.moe_decode_dedup = bool(moe_decode_dedup)
        moe_decode_dedup = self.moe_decode_dedup

        # unified forward dispatch: every compiled step goes through this,
        # so the pipeline schedule slots under the SAME bucketed prefill /
        # block decode / lane machinery as the flat mesh
        h = self.header
        mesh = self.mesh
        sync_quant = self._sync_quant
        if pp > 1:
            from ..parallel.pipeline import forward_pp

            park = self._park if self._lane_pad else 0

            def fwd(params, tokens, pos, cache, *, attn_window=0,
                    logits_mode="all", attn_park_threshold=0, n_micro=1):
                return forward_pp(
                    params, h, tokens, pos, cache, mesh,
                    attn_window=attn_window, logits_mode=logits_mode,
                    attn_park_threshold=attn_park_threshold,
                    n_micro=n_micro, sync_quant=sync_quant,
                    park_pos=park, moe_decode_dedup=moe_decode_dedup,
                )

        else:

            def fwd(params, tokens, pos, cache, *, attn_window=0,
                    logits_mode="all", attn_park_threshold=0, n_micro=1):
                del n_micro  # sequence-wave microbatching is pp-only
                return forward(
                    params, h, tokens, pos, cache, mesh=mesh,
                    attn_window=attn_window, logits_mode=logits_mode,
                    attn_park_threshold=attn_park_threshold,
                    sync_quant=sync_quant,
                    moe_decode_dedup=moe_decode_dedup,
                )

        self._fwd = fwd

    def _pp_micro(self, t: int) -> int:
        """Sequence-wave microbatch count for a T-wide pp prefill chunk:
        prefer ~4 chunks in flight per stage (utilization
        n_micro/(pp+n_micro-1)) while keeping >= 8 rows per wave (flash-
        kernel-friendly; tiny waves would be launch-overhead-bound)."""
        if self.pp == 1 or t < 2 * self.pp:
            return 1
        for k in (4 * self.pp, 2 * self.pp, self.pp):
            if t % k == 0 and t // k >= 8:
                return k
        return 1

    # -- cache ---------------------------------------------------------------

    def _fresh_cache(self):
        # epoch lets callers detect that cached KV state was dropped
        # (api_server clears its prompt cache iff this moved — a
        # ValueError raised inside a guarded dispatch also rebuilds)
        self.cache_epoch = getattr(self, "cache_epoch", -1) + 1
        self._m_epochs.inc()
        self.recorder.record("cache_epoch", epoch=self.cache_epoch)
        cache = init_kv_cache(
            self.header,
            self.batch_size,
            dtype=self.kv_dtype,
            seq_len=self.header.seq_len + self._lane_pad,
        )
        return {
            k: jax.device_put(v, self._cache_sharding[k]) for k, v in cache.items()
        }

    def reset(self) -> None:
        """Drop KV state (new conversation)."""
        self.cache = self._fresh_cache()

    @contextlib.contextmanager
    def _cache_guard(self):
        """Crash consistency for the donated KV cache: every compiled
        step donates `self.cache` (donate_argnums), so a dispatch that
        raises leaves the engine holding buffers in an unknown —
        possibly already-donated — state, and the next call would fail
        on them. Replace with a fresh cache before re-raising, so one
        failed request costs its context but never wedges the engine
        (the reference's analogue re-initializes the whole app every
        3 s on executor errors, src/dllama-api.cpp:616-628; here params
        are never donated, so only the cache needs rebuilding)."""
        try:
            yield
        except BaseException as e:
            self.recorder.record(
                "error", error=str(e), error_type=type(e).__name__
            )
            self.recorder.postmortem("engine-step", e)
            try:
                self.cache = self._fresh_cache()
            except Exception as rebuild_err:  # pragma: no cover
                raise rebuild_err from e
            raise

    def _fault(self, op: str):
        """Chaos hook (runtime/faults.py): the armed fault for this
        dispatch, if any. Callers raise a TRANSIENT fault BEFORE their
        donated-buffer guard (buffers intact, the epoch does not move,
        the scheduler retries) and a POISON fault INSIDE it (the guard
        rebuilds the buffer and the epoch moves — the recovery path)."""
        return get_fault_plane().draw("dispatch", op=op)

    def set_seed(self, seed: int) -> None:
        """Reseed BOTH sampling paths (host xorshift sampler and the
        on-device PRNG used by blocked decode)."""
        self.sampler.set_seed(seed)
        self._base_key = jax.random.PRNGKey(seed)
        self._lane_seed_base = seed
        self._rng_calls = 0

    # -- compiled steps ------------------------------------------------------

    def _attn_window(self, limit: int) -> int:
        """Smallest power-of-2 window >= limit (min 512) covering the live
        cache prefix; full seq_len when nothing smaller fits. One
        compiled program per window keeps decode reads proportional to
        the context actually used instead of the allocated seq_len —
        O(pos) decode reads live HERE, not in a kernel: round-3 silicon
        showed Mosaic does not elide repeated-index DMAs, and windowed
        XLA dense attention beats the Pallas decode kernel
        (scripts/decode_probe.py).

        Under sp the cache uses the CYCLIC sequence layout (global row g
        on shard g % sp at local row g // sp — models/transformer), so a
        window that is an sp x 512 tile is exactly the 512-row local
        prefix of every shard: the live context spreads evenly and
        windowed O(pos) reads survive on the long-context axis (r3
        returned 0 here, re-reading the whole per-shard cache)."""
        s = self.header.seq_len
        if self.sp > 1:
            w = 512 * self.sp
            while w < limit:
                w *= 2
            return min(w, s)
        w = 512
        while w < limit:
            w *= 2
        # NB: crossing a window boundary mid-generation compiles a fresh
        # program for the next window (one synchronous stall per crossing,
        # log2(seq_len/512) of them worst case, amortized by the on-disk
        # compilation cache across runs).
        return min(w, s)

    def _note_window(self, window: int) -> None:
        """Count attention-window growth (each crossing compiles — or
        prefetched — a fresh program; the counter makes the p99 stall
        source visible on `/metrics`)."""
        if (
            self._obs_last_window is not None
            and window > self._obs_last_window
        ):
            self._m_window_crossings.inc()
        self._obs_last_window = window

    def _step_fn(self, t: int, greedy: bool, window: int = 0):
        """Build/jit the forward step for chunk length `t`."""
        key = (t, greedy, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
        precision = self._precision
        fwd = self._fwd

        @partial(jax.jit, donate_argnums=(2,))
        def step(params, tokens, cache, pos):
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                logits, cache = fwd(
                    params, tokens, pos, cache,
                    attn_window=window, logits_mode="last",
                    n_micro=self._pp_micro(t),
                )
            last = logits[:, -1, :]
            if greedy:
                # On-device sampling (reference samples on host from the
                # logits pipe; fusing argmax here avoids the [vocab] device
                # -> host transfer per decoded token).
                return jnp.argmax(last, axis=-1).astype(jnp.int32), cache
            return last, cache

        with self._compile_lock:
            self._compiled[key] = step
            self._compile_origin[key] = "dispatch"
        self._m_compiles.labels(origin="dispatch").inc()
        # lazily jitted: XLA compiles on first call, so there is no build
        # time to record here — one deferred marker instead of start/end
        self.recorder.record(
            "compile", key=str(key), origin="dispatch", deferred=True
        )
        return step

    def _block_arg_specs(self, n_steps: int):
        """ShapeDtypeStructs (with shardings) matching a decode_block
        dispatch exactly — what the AOT pre-compile lowers against. Uses
        the init-time snapshot (`_param_specs`/`_cache_specs`): reading
        `self.cache` here would race the serving thread's donated
        dispatches (a donated buffer deletes mid-read)."""
        tok = jax.ShapeDtypeStruct(
            (self.batch_size, 1), jnp.int32, sharding=self._token_sharding
        )
        # scalars/rng stay UNSHARDED specs: the dispatch passes fresh
        # uncommitted arrays, and pinning a single device here conflicts
        # with multi-device meshes at lowering time
        scalar_i = jax.ShapeDtypeStruct((), jnp.int32)
        scalar_f = jax.ShapeDtypeStruct((), jnp.float32)
        key = jax.random.fold_in(self._base_key, 0)
        rng = jax.ShapeDtypeStruct(key.shape, key.dtype)
        return (
            self._param_specs,
            tok,
            self._cache_specs,
            scalar_i,
            rng,
            scalar_f,
            scalar_f,
        )

    def _decode_block_fn(
        self, n_steps: int, greedy: bool, window: int = 0, origin: str = "dispatch"
    ):
        """Jitted on-device decode of `n_steps` tokens: the sample ->
        feed-back loop runs under `lax.fori_loop`, so the host pays one
        dispatch per block instead of one per token (host->device dispatch
        costs ~10ms/step when the chip sits behind a tunnel; this is the
        lax.fori_loop multi-step plan from SURVEY.md §7 hard parts).
        Sampling (temperature/top-p) runs on device too; temp/topp are
        traced so changing them does not recompile.

        With `_aot_blocks` the program is compiled EAGERLY (AOT lower +
        compile against the live arg specs) and the cache stores the
        executable — which is what lets `_prefetch_block` build the next
        attention window's program off-thread before a lane crosses the
        boundary (no synchronous compile at the crossing)."""
        key = ("block", n_steps, greedy, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:  # a prefetch thread is building it: wait, reuse
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd

        @partial(jax.jit, donate_argnums=(2,))
        def block(params, token, cache, pos, rng, temperature, topp):
            def body(i, carry):
                tok, cache, out = carry
                ctx = (
                    jax.default_matmul_precision(precision)
                    if precision
                    else contextlib.nullcontext()
                )
                with ctx:
                    logits, cache = fwd(
                        params, tok, pos + i, cache,
                        attn_window=window, logits_mode="last",
                    )
                last = logits[:, -1, :]
                if greedy:
                    nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                else:
                    nxt = _sample_on_device(
                        last, temperature, topp, jax.random.fold_in(rng, i)
                    )
                nxt = nxt.reshape(-1, 1)
                out = lax.dynamic_update_index_in_dim(out, nxt[:, 0], i, axis=0)
                return nxt, cache, out

            out0 = jnp.zeros((n_steps, token.shape[0]), jnp.int32)
            tok, cache, out = lax.fori_loop(
                0, n_steps, body, (token, cache, out0)
            )
            return out, cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            block = block.lower(*self._block_arg_specs(n_steps)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = block
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return block

    def _prefetch(self, key, builder) -> None:
        """Compile the NEXT attention window's program in a daemon thread
        (VERDICT r4 #7): called when a lane passes ~75% of the current
        window, so the boundary crossing finds the program in `_compiled`
        instead of stalling a serving-path dispatch on a synchronous XLA
        compile. `builder` must call the matching *_fn with
        origin='prefetch'."""
        import threading

        with self._compile_lock:
            if key in self._compiled or key in self._inflight:
                return
            ev = threading.Event()
            self._inflight[key] = ev

        def work():
            try:
                fault = get_fault_plane().draw("prefetch")
                if fault is not None:
                    raise fault
                builder()
            except Exception:
                # a daemon thread dies silently by default: the boundary
                # crossing would then fall back to a synchronous compile
                # every window with nothing in the logs explaining the p99
                # stalls. Log it and mark the key so telemetry/tests can
                # see the prefetch path is broken.
                import logging

                logging.getLogger(__name__).exception(
                    "AOT prefetch failed for %r; the window boundary will "
                    "compile synchronously",
                    key,
                )
                with self._compile_lock:
                    self._compile_origin[key] = "prefetch-failed"
                self._m_compiles.labels(origin="prefetch-failed").inc()
            finally:
                with self._compile_lock:
                    self._inflight.pop(key, None)
                ev.set()

        # joined via the per-key `ev` Event in _decode_block_fn (the
        # dispatch path waits on it), not via the Thread handle
        threading.Thread(  # dlint: disable=thread-hygiene — lifetime bounded by the _inflight[key] Event; waiters join through ev.wait()
            target=work, daemon=True, name=f"dllama-prefetch-{key[1]}"
        ).start()

    def _prefetch_block(self, n_steps: int, greedy: bool, window: int) -> None:
        self._prefetch(
            ("block", n_steps, greedy, window),
            lambda: self._decode_block_fn(
                n_steps, greedy, window, origin="prefetch"
            ),
        )

    def decode_block(
        self, token: int | list[int], pos: int, n_steps: int
    ) -> list[int] | list[list[int]]:
        """Decode up to `n_steps` tokens in one device dispatch (greedy when
        temperature == 0, on-device temperature/top-p sampling otherwise).

        `token` may be a per-lane list (one independent sequence per batch
        lane, the dp axis); the return is then [n_steps][lanes]."""
        per_lane = isinstance(token, (list, tuple))
        n_steps = self._block_width(pos, n_steps)
        if n_steps <= 0:
            return []
        if per_lane:
            if len(token) != self.batch_size:
                raise ValueError(
                    f"{len(token)} lane tokens for batch_size {self.batch_size}"
                )
            arr = jnp.asarray([[t] for t in token], dtype=jnp.int32)
        else:
            arr = jnp.asarray([[token]] * self.batch_size, dtype=jnp.int32)
        arr = jax.device_put(arr, self._token_sharding)
        greedy = self.temperature == 0.0
        window = self._attn_window(pos + n_steps)
        self._note_window(window)
        block = self._decode_block_fn(n_steps, greedy, window)
        if (
            self._aot_blocks
            and window < self.header.seq_len
            and pos + n_steps >= (3 * window) // 4
        ):
            # past 75% of this window: build the next window's program in
            # the background so the crossing performs no synchronous
            # compile (the window-boundary p99 stall, VERDICT r4 #7)
            self._prefetch_block(n_steps, greedy, self._attn_window(window + 1))
        # fold in a call counter so successive generations differ (the
        # reference's xorshift state advances across calls the same way)
        self._rng_calls += 1
        rng = jax.random.fold_in(
            jax.random.fold_in(self._base_key, pos), self._rng_calls
        )
        self.recorder.record(
            "step_dispatch", step="decode_block", pos=pos,
            n_steps=n_steps, window=window,
        )
        sp = self._spans.begin(
            "decode_block", component="engine", n_steps=n_steps,
            pos=pos, window=window,
        )
        t0 = time.perf_counter()
        with self._cache_guard():
            out, self.cache = block(
                self.params,
                arr,
                self.cache,
                jnp.int32(pos),
                rng,
                jnp.float32(max(self.temperature, 1e-6)),
                jnp.float32(self.sampler.topp),
            )
            # dispatch returned (async); the readback below waits for the
            # device — the ".device" sub-span is that wait
            sp_dev = self._spans.begin(
                "decode_block.device", component="engine"
            )
            out = np.asarray(out)  # [n_steps, lanes]
            self._spans.end(sp_dev)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="decode_block").observe(dt)
        self._m_tpot.observe(dt / n_steps)
        self.recorder.record(
            "step_complete", step="decode_block", pos=pos,
            n_steps=n_steps, window=window, ms=round(dt * 1000, 3),
        )
        if per_lane:
            return [[int(t) for t in row] for row in out]
        return [int(t) for t in out[:, 0]]

    def _score_fn(self, t: int, window: int = 0):
        """Build/jit the teacher-forced scoring step for chunk length `t`:
        returns the summed next-token NLL of the chunk's unmasked rows as
        ONE scalar (no [T, vocab] logits transfer — the reference ships the
        full logits pipe to host per batch, src/dllama.cpp:132-172)."""
        key = ("score", t, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
        precision = self._precision
        fwd = self._fwd

        @partial(jax.jit, donate_argnums=(4,))
        def score(params, tokens, targets, mask, cache, pos):
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                logits, cache = fwd(
                    params, tokens, pos, cache, attn_window=window,
                    n_micro=self._pp_micro(t),
                )
            lg = logits.astype(jnp.float32)  # [B, T, V]
            lse = jax.nn.logsumexp(lg, axis=-1)  # [B, T]
            tgt = jnp.take_along_axis(lg, targets[..., None], axis=-1)[..., 0]
            nll = (lse - tgt) * mask
            return jnp.sum(nll[0]), cache

        with self._compile_lock:
            self._compiled[key] = score
            self._compile_origin[key] = "dispatch"
        self._m_compiles.labels(origin="dispatch").inc()
        self.recorder.record(
            "compile", key=str(key), origin="dispatch", deferred=True
        )
        return score

    def perplexity(self, tokens: list[int]) -> tuple[float, float, int]:
        """Teacher-forced (nll, perplexity, n_scored) over `tokens`,
        scored chunk-by-chunk through the bucketed prefill programs — the
        result is chunk-size invariant and compiles only bucket-shaped
        programs (the reference walks the prompt in nBatches chunks the
        same way, src/dllama.cpp:132-172)."""
        t = len(tokens)
        if t < 2:
            raise ValueError("need at least 2 tokens for perplexity")
        if t > self.header.seq_len:
            raise ValueError(
                f"{t} tokens exceed seqLen {self.header.seq_len}"
            )
        bad = max(tokens)
        if bad >= self.header.vocab_size:
            # a tokenizer/model vocab mismatch would otherwise score
            # out-of-range rows as NaN (gather clamps silently on device)
            raise ValueError(
                f"token id {bad} out of range for model vocab "
                f"{self.header.vocab_size} (tokenizer/model mismatch?)"
            )
        self.reset()
        nll_sum = 0.0
        p = 0
        remaining = list(tokens)
        while remaining:
            bucket = self._bucket_for(len(remaining), p)
            width = min(bucket, len(remaining))
            chunk = remaining[:width] + [0] * (bucket - width)
            remaining = remaining[width:]
            # row j (global index p+j) is scored against token p+j+1; the
            # final token and padding rows are masked out
            targets = [
                tokens[p + j + 1] if (p + j + 1 < t and j < width) else 0
                for j in range(bucket)
            ]
            mask = [
                1.0 if (p + j + 1 < t and j < width) else 0.0
                for j in range(bucket)
            ]
            arr = jax.device_put(
                jnp.asarray([chunk] * self.batch_size, jnp.int32),
                self._token_sharding,
            )
            tgt = jax.device_put(
                jnp.asarray([targets] * self.batch_size, jnp.int32),
                self._token_sharding,
            )
            msk = jax.device_put(
                jnp.asarray([mask] * self.batch_size, jnp.float32),
                self._token_sharding,
            )
            score = self._score_fn(
                bucket, window=self._attn_window(p + bucket)
            )
            with self._cache_guard():
                part, self.cache = score(
                    self.params, arr, tgt, msk, self.cache, jnp.int32(p)
                )
                nll_sum += float(np.asarray(part))
            p += width
        n_scored = t - 1
        nll = nll_sum / n_scored
        return nll, float(np.exp(nll)), n_scored

    # -- per-lane serving (continuous-batching surface) ----------------------

    def _require_lanes(self) -> None:
        if self._lane_pad == 0:
            raise ValueError(
                "per-lane serving needs batch_size > 1 "
                "(lanes park their writes in cache padding rows)"
            )

    def _lane_prefill_arg_specs(self, t: int):
        """Arg specs for a lane-prefill chunk dispatch (the AOT lowering
        input): token rows are (lanes, bucket) with the lane sharding, the
        position vector is per-lane, and the params/cache trees come from
        the init-time snapshot (same no-donated-reads rule as
        _lane_arg_specs — rehearsal threads must never read live trees a
        serving dispatch is donating)."""
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, t), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._param_specs,
            tok,
            self._cache_specs,
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )

    def _lane_prefill_fn(
        self, t: int, window: int = 0, origin: str = "dispatch"
    ):
        """Vector-position prefill step: each lane writes its chunk at its
        own position; parked lanes write into the padding rows.
        AOT-compiled like the decode blocks — this is the lane scheduler's
        ADMISSION path, so a synchronous XLA compile here is exactly the
        first-admission stall rehearse_admission() exists to remove."""
        key = ("lane_prefill", t, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:  # a rehearsal thread is building it: wait, reuse
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd
        park = self._park

        @partial(jax.jit, donate_argnums=(2,))
        def step(params, tokens, cache, pos_vec):
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                _, cache = fwd(
                    params, tokens, pos_vec, cache,
                    attn_window=window, attn_park_threshold=park,
                    logits_mode="last", n_micro=self._pp_micro(t),
                )
            return cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            step = step.lower(*self._lane_prefill_arg_specs(t)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = step
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return step

    def rehearse_admission(
        self,
        block_size: int | None = None,
        spec_k: int = 0,
        wait: bool = False,
    ) -> None:
        """Pre-compile the admission-path programs in the background: one
        lane-prefill chunk program per configured bucket (at the bucket's
        base attention window) plus the lane decode block — and, when
        speculation is on (spec_k > 0), one verify program per draft
        bucket — so the FIRST admission under load finds them in the
        cache instead of paying a synchronous compile stall on the
        serving path. No-op without AOT blocks
        (DLLAMA_WINDOW_PRECOMPILE=0): the lazily jitted programs then
        compile at first dispatch as before.

        ``wait=True`` blocks until every scheduled compile has finished
        (successfully or not) — what the xlalint CLI and the clean-engine
        smoke test use to lint a deterministic program set."""
        self._require_lanes()
        if not self._aot_blocks:
            return
        native = self.kv_native
        for bucket in self.prefill_buckets:
            window = self._attn_window(bucket)
            if native:
                self._prefetch(
                    ("lane_prefill_paged", bucket, window),
                    lambda b=bucket, w=window: self._lane_prefill_paged_fn(
                        b, window=w, origin="prefetch"
                    ),
                )
            else:
                self._prefetch(
                    ("lane_prefill", bucket, window),
                    lambda b=bucket, w=window: self._lane_prefill_fn(
                        b, window=w, origin="prefetch"
                    ),
                )
        if block_size:
            window = self._attn_window(block_size)
            if native:
                self._prefetch(
                    ("lane_block_paged", block_size, window),
                    lambda n=block_size, w=window: self._lane_decode_paged_fn(
                        n, w, origin="prefetch"
                    ),
                )
            else:
                self._prefetch(
                    ("lane_block", block_size, window),
                    lambda n=block_size, w=window: self._lane_decode_fn(
                        n, w, origin="prefetch"
                    ),
                )
        if spec_k > 0:
            # one verify program per draft bucket (width 1 + bucket for
            # the pending token) at the base window; deeper windows ride
            # the same 75% prefetch as the decode block
            from .spec import spec_buckets

            for kb in spec_buckets(min(spec_k, self._lane_pad - 1)):
                t = kb + 1
                window = self._attn_window(t)
                if native:
                    self._prefetch(
                        ("lane_verify_paged", t, window),
                        lambda tt=t, w=window: self._lane_verify_paged_fn(
                            tt, w, origin="prefetch"
                        ),
                    )
                else:
                    self._prefetch(
                        ("lane_verify", t, window),
                        lambda tt=t, w=window: self._lane_verify_fn(
                            tt, w, origin="prefetch"
                        ),
                    )
        if spec_k > 0 and self._draft_params is not None:
            # resident draft model: its catch-up prefill buckets and
            # k-step propose blocks sit on the serving path exactly like
            # the verify programs — pre-build them all (they are tiny)
            from .spec import spec_buckets as _sb

            dseq = self._draft_header.seq_len
            for bucket in self.prefill_buckets:
                if bucket > dseq:
                    continue
                self._prefetch(
                    ("draft_prefill", bucket),
                    lambda b=bucket: self._draft_prefill_fn(
                        b, origin="prefetch"
                    ),
                )
            for kb in _sb(min(spec_k, self._lane_pad - 1)):
                self._prefetch(
                    ("draft_step", kb),
                    lambda n=kb: self._draft_step_fn(n, origin="prefetch"),
                )
        if self.kv_pool is not None and native:
            # the only device copy left on the native path: the COW fork
            # of a mid-page adoption boundary (one page at a time)
            self._prefetch(
                ("kv_page_copy", 1),
                lambda: self._kv_page_copy_fn(1, origin="prefetch"),
            )
        elif self.kv_pool is not None:
            # page-copy programs sit on the admission (adopt) and finish
            # (publish) paths; pre-build every power-of-two bucket up to a
            # full sequence's page count
            max_pages = max(1, self.header.seq_len // self._kv_page_size)
            b = 1
            while b <= max_pages:
                for kind in ("adopt", "publish"):
                    self._prefetch(
                        ("kv_" + kind, b),
                        lambda k=kind, n=b: self._kv_copy_fn(
                            k, n, origin="prefetch"
                        ),
                    )
                b *= 2
        if wait:
            # drain the prefetch threads: snapshot under the lock, wait
            # outside it (builders need the lock to finish), repeat until
            # nothing is in flight
            while True:
                with self._compile_lock:
                    pending = list(self._inflight.values())
                if not pending:
                    return
                for ev in pending:
                    ev.wait()

    def prefill_lane_chunk(
        self,
        lane: int,
        tokens: list[int],
        pos0: int,
        budget: int | None = None,
    ) -> int:
        """Write ONE bucket-shaped chunk of `tokens` (fill rows — the
        caller already dropped the prompt's final token) into `lane`'s
        cache at `pos0`; returns how many tokens were consumed. This is
        the resumable half of prefill_lane: the lane scheduler dispatches
        one chunk per loop tick so a long prompt's admission interleaves
        with decode blocks instead of freezing every active lane for the
        whole prefill. `budget` caps the chunk width (--admission-chunk).
        Chunks reuse the same _lane_prefill_fn bucket programs as the
        monolithic path — no new compiled shapes — and write the same KV
        rows, so chunked admission is token-exact vs monolithic."""
        self._require_lanes()
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        n = len(tokens)
        if n < 1:
            raise ValueError("empty chunk")
        if pos0 + n > self.header.seq_len:
            raise ValueError(
                f"{n} fill tokens at pos {pos0} exceed "
                f"seqLen {self.header.seq_len}"
            )
        fault = self._fault("prefill_lane_chunk")
        if fault is not None and not fault.poison:
            raise fault
        want = min(n, budget) if budget and budget > 0 else n
        bucket = self._bucket_for(want, pos0)
        width = min(bucket, want)
        chunk = tokens[:width] + [0] * (bucket - width)
        rows = [[0] * bucket for _ in range(self.batch_size)]
        rows[lane] = chunk
        window = self._attn_window(pos0 + bucket)
        native = self.kv_native
        # the paged view parks at `window` (its tail rows); the slab
        # parks at seq_len (its padding rows)
        posv = [window if native else self._park] * self.batch_size
        posv[lane] = pos0
        step = (
            self._lane_prefill_paged_fn(bucket, window=window)
            if native
            else self._lane_prefill_fn(bucket, window=window)
        )
        self.recorder.record(
            "step_dispatch", step="prefill_lane_chunk", lane=lane, pos=pos0,
            n_tokens=width, bucket=bucket, window=window,
        )
        sp = self._spans.begin(
            "prefill_lane_chunk", component="engine", lane=lane,
            pos=pos0, n_tokens=width, bucket=bucket,
        )
        t0 = time.perf_counter()
        arr = jax.device_put(
            jnp.asarray(rows, jnp.int32), self._token_sharding
        )
        pos_arr = jnp.asarray(posv, jnp.int32)
        if native:
            with self._kv_pool_guard():
                if fault is not None:
                    raise fault
                self.kv_pool = step(
                    self.params, arr, self.kv_pool,
                    jnp.asarray(self._page_table), pos_arr,
                )
        else:
            with self._cache_guard():
                if fault is not None:
                    raise fault
                self.cache = step(self.params, arr, self.cache, pos_arr)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="prefill_lane_chunk").observe(dt)
        self.recorder.record(
            "step_complete", step="prefill_lane_chunk", lane=lane, pos=pos0,
            n_tokens=width, ms=round(dt * 1000, 3),
        )
        return width

    def prefill_lane(self, lane: int, tokens: list[int], pos0: int = 0) -> None:
        """Prefill one lane's prompt (all but the last token) while every
        other lane's cache rows stay untouched — their writes land in the
        padding rows beyond seqLen, and causal masking hides those rows
        from every real query. This is what lets the API server admit a
        new request into a free lane while other lanes hold live
        conversations (the reference's single-stream loop has no
        equivalent). Runs the chunks back-to-back; the lane scheduler
        instead calls prefill_lane_chunk directly to interleave them with
        decode blocks."""
        self._require_lanes()
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prompt")
        if pos0 + n - 1 > self.header.seq_len:
            raise ValueError(
                f"prompt of {n} tokens at pos {pos0} exceeds "
                f"seqLen {self.header.seq_len}"
            )
        fills = tokens[:-1]
        p = pos0
        self.recorder.record(
            "step_dispatch", step="prefill_lane", lane=lane, pos=pos0,
            n_tokens=len(fills),
        )
        t0 = time.perf_counter()
        while fills:
            width = self.prefill_lane_chunk(lane, fills, p)
            fills = fills[width:]
            p += width
        if p > pos0:
            dt = time.perf_counter() - t0
            self._m_step.labels(kind="prefill_lane").observe(dt)
            self.recorder.record(
                "step_complete", step="prefill_lane", lane=lane, pos=pos0,
                n_tokens=p - pos0, ms=round(dt * 1000, 3),
            )

    # -- paged KV pool (cross-lane prefix sharing) ---------------------------

    def _require_kv_pool(self) -> None:
        if self.kv_pool is None:
            raise ValueError("KV page pool not initialized (init_kv_pool)")

    def _kv_pool_sharding(self) -> NamedSharding:
        # mirror the cache's lead (pp stage) and tp (kv-head) axes; the
        # page axis replaces the dp batch axis and stays replicated —
        # pages are lane-free, that is the whole point
        lead = "pp" if self.pp > 1 else None
        return NamedSharding(self.mesh, P(lead, None, "tp", None, None))

    def _alloc_kv_pool(self):
        from ..ops.kv_cache import QuantKV

        h = self.header
        sharding = self._kv_pool_sharding()
        shape = (
            h.n_layers, self._kv_pool_pages, h.n_kv_heads,
            self._kv_page_size, h.head_dim,
        )
        if self.kv_dtype == jnp.int8:
            def leaf():
                return QuantKV(
                    jax.device_put(jnp.zeros(shape, jnp.int8), sharding),
                    jax.device_put(
                        jnp.ones(shape[:-1] + (1,), jnp.float32), sharding
                    ),
                )

            return {"k": leaf(), "v": leaf()}
        return {
            k: jax.device_put(jnp.zeros(shape, self.kv_dtype), sharding)
            for k in ("k", "v")
        }

    def init_kv_pool(
        self, page_size: int, n_pages: int = 0, native: bool = False
    ) -> int:
        """Allocate the shared KV page pool: ``[L, n_pages, KH, page_size,
        hd]`` per k/v leaf (QuantKV pairs under int8 KV), replicated over
        the page axis and sharded like the cache elsewhere. Page 0 is the
        scratch page bucketed copy programs pad with. ``n_pages`` <= 0
        picks a budget of two full-length sequences' worth of pages (in
        native mode: one sequence per lane plus two shareable sequences,
        since the pool is then the only KV home). ``native=True`` switches
        decode/verify/prefill to the pool-native paged programs; each lane
        reads K/V through its page-table row instead of its slab rows.
        Returns the page count actually allocated."""
        self._require_lanes()
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if page_size > self._lane_pad:
            # the bucketed copy loop guarantees (start + bucket) * ps never
            # exceeds seq_len + lane_pad only when one page fits in the
            # padding (dynamic_slice would clamp silently and misalign)
            raise ValueError(
                f"page_size {page_size} exceeds lane padding {self._lane_pad}"
            )
        if native and (self.pp > 1 or self.sp > 1):
            # the pp fwd closure parks at the slab's seq_len and sp shards
            # the sequence axis; both assume slab geometry — the native
            # paged view parks at `window` and is unsharded on its row axis
            raise ValueError("kv_native requires pp == 1 and sp == 1")
        n_blocks = -(-self.header.seq_len // page_size)
        if n_pages <= 0:
            if native:
                n_pages = (self.batch_size + 2) * n_blocks + 1
            else:
                n_pages = 2 * (self.header.seq_len // page_size) + 1
        self._kv_page_size = page_size
        self._kv_pool_pages = n_pages
        self._kv_n_blocks = n_blocks
        self.kv_native = bool(native)
        self._page_table = np.zeros((self.batch_size, n_blocks), np.int32)
        self.kv_pool = self._alloc_kv_pool()
        self._kv_pool_specs = jax.tree.map(_sds, self.kv_pool)
        self.kv_pool_epoch += 1
        return n_pages

    def reset_kv_pool(self) -> None:
        """Reallocate the pool buffer (all page contents dropped). The
        caller owns resetting its host-side page/radix accounting to
        match."""
        self._require_kv_pool()
        self.kv_pool = self._alloc_kv_pool()
        self.kv_pool_epoch += 1
        if self._page_table is not None:
            self._page_table[:] = 0

    def adopt_pages(self, lane: int, page_ids: list[int]) -> None:
        """Pool-native kv_adopt: point ``lane``'s page-table row at
        ``page_ids`` (slot i backs rows [i*ps, (i+1)*ps)). No device work
        — this is the whole point. Unfilled slots fall back to the scratch
        page 0, which only ever receives parked/out-of-range writes."""
        self._require_kv_pool()
        if not self.kv_native:
            raise ValueError("adopt_pages requires kv_native mode")
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        if len(page_ids) > self._kv_n_blocks:
            raise ValueError(
                f"{len(page_ids)} pages exceed {self._kv_n_blocks} blocks"
            )
        row = self._page_table[lane]
        row[:] = 0
        if page_ids:
            row[: len(page_ids)] = np.asarray(page_ids, np.int32)
        self.recorder.record(
            "step_complete", step="kv_adopt", lane=lane,
            n_pages=len(page_ids), ms=0.0, native=True,
        )

    def clear_lane_pages(self, lane: int) -> None:
        """Drop ``lane``'s page-table row (back to the scratch page)."""
        if self._page_table is not None:
            self._page_table[lane] = 0

    def clear_all_lane_pages(self) -> None:
        if self._page_table is not None:
            self._page_table[:] = 0

    def _kv_page_bytes(self) -> int:
        """Device bytes per pool page, summed over k/v (and QuantKV
        scale) leaves and all layers — the unit dllama_kv_copy_bytes_total
        counts in."""
        total = 0
        for leaf in jax.tree.leaves(self._kv_pool_specs):
            n = 1
            for i, d in enumerate(leaf.shape):
                if i != 1:  # every axis but the page axis
                    n *= d
            total += n * jnp.dtype(leaf.dtype).itemsize
        return total

    @contextlib.contextmanager
    def _kv_pool_guard(self):
        """Crash consistency for the donated pool buffer (the publish
        program's analogue of _cache_guard): a failed dispatch may leave
        the pool half-donated, so rebuild it before re-raising. Host-side
        accounting is the manager's to reset. kv_pool_epoch moves so the
        manager can tell pool-poisoning failures from transient ones; in
        native mode cache_epoch moves too — the pool IS the lane KV, so
        the scheduler's existing poisoned/transient classification keeps
        working unchanged."""
        try:
            yield
        except BaseException as e:
            self.recorder.record(
                "error", error=str(e), error_type=type(e).__name__
            )
            try:
                self.kv_pool = self._alloc_kv_pool()
            except Exception as rebuild_err:  # pragma: no cover
                raise rebuild_err from e
            self.kv_pool_epoch += 1
            if self.kv_native:
                self.cache_epoch += 1
                self._m_epochs.inc()
                self.recorder.record(
                    "cache_epoch", epoch=self.cache_epoch, native=True
                )
            raise

    def _kv_copy_arg_specs(self, bucket: int):
        return (
            self._cache_specs,
            self._kv_pool_specs,
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((bucket,), jnp.int32),
        )

    def _kv_copy_fn(self, kind: str, bucket: int, origin: str = "dispatch"):
        """Jitted page-copy program: ``adopt`` gathers ``bucket`` pool
        pages into a lane's slab rows ``[start*ps, (start+bucket)*ps)``
        (donates the cache), ``publish`` scatters those slab rows into
        pool pages (donates the pool). One program per (kind, bucket) —
        bucketed like prefill so the compile-cache footprint stays
        O(log max_pages). QuantKV caches work unchanged: jax.tree.map
        descends into the (values, scales) pair and every op below is
        shape-generic in the trailing dim."""
        key = ("kv_" + kind, bucket)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        ps = self._kv_page_size

        if kind == "adopt":

            @partial(jax.jit, donate_argnums=(0,))
            def fn(cache, pool, lane, start_page, ids):
                def leaf(c, p):
                    pages = p[:, ids]  # [L, bucket, KH, ps, last]
                    l_, _, kh, _, last = pages.shape
                    rows = pages.transpose(0, 2, 1, 3, 4).reshape(
                        l_, 1, kh, bucket * ps, last
                    )
                    return lax.dynamic_update_slice(
                        c, rows, (0, lane, 0, start_page * ps, 0)
                    )

                return jax.tree.map(leaf, cache, pool)

        elif kind == "publish":

            @partial(jax.jit, donate_argnums=(1,))
            def fn(cache, pool, lane, start_page, ids):
                def leaf(c, p):
                    l_, _, kh, _, last = c.shape
                    rows = lax.dynamic_slice(
                        c, (0, lane, 0, start_page * ps, 0),
                        (l_, 1, kh, bucket * ps, last),
                    )
                    pages = rows[:, 0].reshape(
                        l_, kh, bucket, ps, last
                    ).transpose(0, 2, 1, 3, 4)
                    return p.at[:, ids].set(pages)

                return jax.tree.map(leaf, cache, pool)

        else:
            raise ValueError(f"unknown kv copy kind {kind!r}")

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            fn = fn.lower(*self._kv_copy_arg_specs(bucket)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = fn
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return fn

    def _kv_copy_chunks(self, n: int):
        """Decompose an n-page copy into decreasing power-of-two buckets.
        Running largest-first keeps start+bucket <= n at every step, so
        with page_size <= lane_pad no dynamic_slice can reach past the
        slab (where it would clamp silently and misalign rows)."""
        out, start = [], 0
        while start < n:
            b = 1
            while b * 2 <= n - start:
                b *= 2
            out.append((start, b))
            start += b
        return out

    def kv_adopt(self, lane: int, page_ids: list[int]) -> None:
        """Copy pool pages into ``lane``'s slab rows ``[0, n*ps)`` — the
        admission half of prefix sharing: the lane starts its life with
        the shared prefix's KV already in place and only the unmatched
        suffix is prefilled. Rows of a partial final page beyond the
        matched token count hold the donor's stale tail; they are
        overwritten by suffix prefill before any query position can
        attend to them (the parked-row garbage argument)."""
        self._require_kv_pool()
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        n = len(page_ids)
        if n < 1:
            raise ValueError("empty page list")
        if n * self._kv_page_size > self.header.seq_len:
            raise ValueError(f"{n} pages exceed seqLen {self.header.seq_len}")
        fault = self._fault("kv_adopt")
        if fault is not None and not fault.poison:
            raise fault
        self.recorder.record(
            "step_dispatch", step="kv_adopt", lane=lane, n_pages=n
        )
        sp = self._spans.begin(
            "kv_adopt", component="engine", lane=lane, n_pages=n
        )
        t0 = time.perf_counter()
        for start, bucket in self._kv_copy_chunks(n):
            fn = self._kv_copy_fn("adopt", bucket)
            ids = jnp.asarray(page_ids[start : start + bucket], jnp.int32)
            with self._cache_guard():
                if fault is not None:
                    raise fault
                self.cache = fn(
                    self.cache, self.kv_pool,
                    jnp.int32(lane), jnp.int32(start), ids,
                )
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="kv_adopt").observe(dt)
        self._m_kv_copy_bytes.inc(n * self._kv_page_bytes())
        self.recorder.record(
            "step_complete", step="kv_adopt", lane=lane, n_pages=n,
            ms=round(dt * 1000, 3),
        )

    def kv_publish(
        self, lane: int, page_ids: list[int], start_page: int
    ) -> None:
        """Scatter ``lane``'s slab rows ``[start_page*ps, ...)`` into pool
        pages — the finish half of prefix sharing: a completed stream's
        full-page KV becomes adoptable by every later admission. The
        caller dedups against the radix tree first, so only slots the
        tree does not already hold are written."""
        self._require_kv_pool()
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        n = len(page_ids)
        if n < 1:
            raise ValueError("empty page list")
        if (start_page + n) * self._kv_page_size > self.header.seq_len:
            raise ValueError(
                f"pages [{start_page}, {start_page + n}) exceed "
                f"seqLen {self.header.seq_len}"
            )
        fault = self._fault("kv_publish")
        if fault is not None and not fault.poison:
            raise fault
        self.recorder.record(
            "step_dispatch", step="kv_publish", lane=lane, n_pages=n,
            start_page=start_page,
        )
        sp = self._spans.begin(
            "kv_publish", component="engine", lane=lane, n_pages=n,
            start_page=start_page,
        )
        t0 = time.perf_counter()
        for off, bucket in self._kv_copy_chunks(n):
            fn = self._kv_copy_fn("publish", bucket)
            ids = jnp.asarray(page_ids[off : off + bucket], jnp.int32)
            with self._kv_pool_guard():
                if fault is not None:
                    raise fault
                self.kv_pool = fn(
                    self.cache, self.kv_pool,
                    jnp.int32(lane), jnp.int32(start_page + off), ids,
                )
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="kv_publish").observe(dt)
        self._m_kv_copy_bytes.inc(n * self._kv_page_bytes())
        self.recorder.record(
            "step_complete", step="kv_publish", lane=lane, n_pages=n,
            ms=round(dt * 1000, 3),
        )

    # -- pool-native paged programs (ISSUE 16) -------------------------------
    #
    # In kv_native mode the pool is the only KV home: each compiled lane
    # program GATHERS the window's pages through the per-lane page table
    # into a contiguous [L, B, KH, window + T, hd] view, runs the exact
    # slab loop body on that view (so live lanes see bit-identical K/V
    # rows and produce bit-identical logits), and SCATTERS the rows it
    # wrote back to the lanes' private pages. Rows at-or-beyond `window`
    # are the view's parking tail (the slab parks at seq_len; the view
    # parks at `window`) and are never scattered — parked/out-of-range
    # garbage stays in the discarded view copy.

    def _kv_page_copy_arg_specs(self, bucket: int):
        return (
            self._kv_pool_specs,
            jax.ShapeDtypeStruct((bucket,), jnp.int32),
            jax.ShapeDtypeStruct((bucket,), jnp.int32),
        )

    def _kv_page_copy_fn(self, bucket: int = 1, origin: str = "dispatch"):
        """Pool-internal page copy (src pages -> dst pages), donating the
        pool: the COW fork of a mid-page adoption tail — the ONLY device
        copy left on the native admission path."""
        key = ("kv_page_copy", bucket)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]

        @partial(jax.jit, donate_argnums=(0,))
        def fn(pool, src, dst):
            def leaf(p):
                return p.at[:, dst].set(p[:, src])

            return jax.tree.map(leaf, pool)

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            fn = fn.lower(*self._kv_page_copy_arg_specs(bucket)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = fn
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return fn

    def kv_page_copy(self, src_ids: list[int], dst_ids: list[int]) -> None:
        """Copy pool pages ``src_ids[i]`` -> ``dst_ids[i]`` on device."""
        self._require_kv_pool()
        n = len(src_ids)
        if n < 1 or len(dst_ids) != n:
            raise ValueError("src/dst page lists must match and be non-empty")
        fault = self._fault("kv_page_copy")
        if fault is not None and not fault.poison:
            raise fault
        self.recorder.record(
            "step_dispatch", step="kv_page_copy", n_pages=n
        )
        sp = self._spans.begin(
            "kv_page_copy", component="engine", n_pages=n
        )
        t0 = time.perf_counter()
        for start, bucket in self._kv_copy_chunks(n):
            fn = self._kv_page_copy_fn(bucket)
            src = jnp.asarray(src_ids[start : start + bucket], jnp.int32)
            dst = jnp.asarray(dst_ids[start : start + bucket], jnp.int32)
            with self._kv_pool_guard():
                if fault is not None:
                    raise fault
                self.kv_pool = fn(self.kv_pool, src, dst)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="kv_page_copy").observe(dt)
        self._m_kv_copy_bytes.inc(n * self._kv_page_bytes())
        self.recorder.record(
            "step_complete", step="kv_page_copy", n_pages=n,
            ms=round(dt * 1000, 3),
        )

    def _paged_gather(self, pool, pt, window: int, tail: int):
        """Contiguous per-lane KV view of the first `window` rows plus a
        `tail`-row parking pad, gathered through the page table."""
        ps = self._kv_page_size
        wb = -(-window // ps)

        def leaf(p):
            pages = p[:, pt[:, :wb]]  # [L, B, wb, KH, ps, last]
            l_, b, _, kh, _, last = pages.shape
            rows = pages.transpose(0, 1, 3, 2, 4, 5).reshape(
                l_, b, kh, wb * ps, last
            )
            rows = rows[:, :, :, :window, :]
            pad = jnp.zeros((l_, b, kh, tail, last), p.dtype)
            return jnp.concatenate([rows, pad], axis=3)

        return jax.tree.map(leaf, pool)

    def _paged_scatter(self, pool, view, pt, rows, safe):
        """Write view rows back to the pool: view row `rows[b, t]` of lane
        b lands in that lane's page-table page for slot rows//ps at page
        row rows%ps. Unsafe entries (parked lanes, rows at-or-beyond the
        window) collapse onto the scratch page 0 — a don't-care row no
        live read ever resolves to. Safe rows always map to lane-PRIVATE
        pages (the manager COW-forks a shared mid-page boundary before
        admission), so cross-lane scatter collisions cannot happen."""
        ps = self._kv_page_size
        nb = pt.shape[1]
        slot = jnp.clip(rows // ps, 0, nb - 1)
        page = jnp.where(safe, jnp.take_along_axis(pt, slot, axis=1), 0)
        prow = jnp.where(safe, rows % ps, 0)
        srow = jnp.where(safe, rows, 0)

        def leaf(p, v):
            vals = jnp.take_along_axis(
                v, srow[None, :, None, :, None], axis=3
            )  # [L, B, KH, T, last]
            return p.at[:, page, :, prow, :].set(
                vals.transpose(1, 3, 0, 2, 4)
            )

        return jax.tree.map(leaf, pool, view)

    def _lane_paged_specs(self, t: int):
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, t), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._param_specs,
            tok,
            self._kv_pool_specs,
            jax.ShapeDtypeStruct((b, self._kv_n_blocks), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )

    def _lane_decode_paged_arg_specs(self, n_steps: int):
        b = self.batch_size
        return self._lane_paged_specs(1) + (
            jax.ShapeDtypeStruct((b,), jnp.bool_),
            jax.ShapeDtypeStruct((b,), jnp.int32),  # per-lane seeds
            jax.ShapeDtypeStruct((b,), jnp.float32),
            jax.ShapeDtypeStruct((b,), jnp.float32),
        )

    def _lane_decode_paged_fn(
        self, n_steps: int, window: int, origin: str = "dispatch"
    ):
        """Pool-native decode block: _lane_decode_fn's loop body run on
        the gathered page view (donating the POOL, not the slab). Live
        lanes read/write the exact rows the slab program would, so the
        emitted tokens are bit-identical; the slab cache is untouched."""
        key = ("lane_block_paged", n_steps, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd
        seq_len = self.header.seq_len

        @partial(jax.jit, donate_argnums=(2,))
        def block(
            params, token, pool, pt, pos_vec, active, seeds,
            temperature, topp,
        ):
            view = self._paged_gather(pool, pt, window, n_steps)

            def body(i, carry):
                tok, view, out = carry
                ok = jnp.logical_and(active, pos_vec + i < seq_len)
                cur = jnp.where(ok, pos_vec + i, window)
                ctx = (
                    jax.default_matmul_precision(precision)
                    if precision
                    else contextlib.nullcontext()
                )
                with ctx:
                    logits, view = fwd(
                        params, tok, cur, view,
                        attn_window=window,
                        attn_park_threshold=window, logits_mode="last",
                    )
                last = logits[:, -1, :]
                nxt = _sample_per_lane(last, temperature, topp, seeds, cur)
                nxt = jnp.where(ok, nxt, 0).reshape(-1, 1)
                out = lax.dynamic_update_index_in_dim(
                    out, nxt[:, 0], i, axis=0
                )
                return nxt, view, out

            out0 = jnp.zeros((n_steps, token.shape[0]), jnp.int32)
            _, view, out = lax.fori_loop(
                0, n_steps, body, (token, view, out0)
            )
            rows = pos_vec[:, None] + jnp.arange(n_steps)[None, :]
            safe = jnp.logical_and(active[:, None], rows < window)
            pool = self._paged_scatter(pool, view, pt, rows, safe)
            return out, pool

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            block = block.lower(
                *self._lane_decode_paged_arg_specs(n_steps)
            ).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = block
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return block

    def _lane_verify_paged_arg_specs(self, t: int):
        b = self.batch_size
        return self._lane_paged_specs(t) + (
            jax.ShapeDtypeStruct((b,), jnp.bool_),
        )

    def _lane_verify_paged_fn(
        self, t: int, window: int, origin: str = "dispatch"
    ):
        """Pool-native speculative verify: _lane_verify_fn on the page
        view (one fwd over t tokens, greedy argmax grid back)."""
        key = ("lane_verify_paged", t, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd
        seq_len = self.header.seq_len

        @partial(jax.jit, donate_argnums=(2,))
        def vstep(params, tokens, pool, pt, pos_vec, active):
            view = self._paged_gather(pool, pt, window, t)
            cur = jnp.where(active, pos_vec, window)
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                logits, view = fwd(
                    params, tokens, cur, view,
                    attn_window=window, attn_park_threshold=window,
                    logits_mode="all", n_micro=self._pp_micro(t),
                )
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out = jnp.where(active[:, None], out, 0)
            rows = cur[:, None] + jnp.arange(t)[None, :]
            safe = jnp.logical_and(
                jnp.logical_and(active[:, None], rows < window),
                rows < seq_len,
            )
            pool = self._paged_scatter(pool, view, pt, rows, safe)
            return out, pool

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            vstep = vstep.lower(
                *self._lane_verify_paged_arg_specs(t)
            ).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = vstep
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return vstep

    def _lane_prefill_paged_fn(
        self, t: int, window: int, origin: str = "dispatch"
    ):
        """Pool-native lane-prefill chunk: _lane_prefill_fn on the page
        view. Parked lanes are fed pos = `window` (the view's parking
        tail), so their writes never scatter back."""
        key = ("lane_prefill_paged", t, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd

        @partial(jax.jit, donate_argnums=(2,))
        def step(params, tokens, pool, pt, pos_vec):
            view = self._paged_gather(pool, pt, window, t)
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                _, view = fwd(
                    params, tokens, pos_vec, view,
                    attn_window=window, attn_park_threshold=window,
                    logits_mode="last", n_micro=self._pp_micro(t),
                )
            rows = pos_vec[:, None] + jnp.arange(t)[None, :]
            safe = rows < window
            pool = self._paged_scatter(pool, view, pt, rows, safe)
            return pool

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            step = step.lower(*self._lane_paged_specs(t)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = step
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return step

    def _lane_arg_specs(self, n_steps: int):
        """Arg specs for a decode_lanes dispatch (the AOT pre-compile's
        lowering input); per-lane vectors stay unsharded like the
        scalars in _block_arg_specs, and the params/cache trees come from
        the init-time snapshot for the same no-donated-reads reason."""
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, 1), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._param_specs,
            tok,
            self._cache_specs,
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.bool_),
            jax.ShapeDtypeStruct((b,), jnp.int32),  # per-lane seeds
            jax.ShapeDtypeStruct((b,), jnp.float32),
            jax.ShapeDtypeStruct((b,), jnp.float32),
        )

    def _lane_decode_fn(
        self, n_steps: int, window: int = 0, origin: str = "dispatch"
    ):
        """Per-lane block decode: every lane advances from its own
        position; inactive lanes are parked (fed token 0, writing only
        padding rows). Sampling settings are per-lane vectors (temperature
        0 = greedy argmax inside _sample_on_device), so ONE compiled
        program serves any mix of requests. One host dispatch per block,
        like decode_block. `window` bounds attention reads by the deepest
        live lane (parked writes land beyond seq_len and are causally
        masked, so the window only limits reads). AOT-compiled like
        _decode_block_fn so the API server's window crossings can be
        prefetched too (this IS the serving path)."""
        key = ("lane_block", n_steps, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd
        park = self._park

        seq_len = self.header.seq_len

        @partial(jax.jit, donate_argnums=(2,))
        def block(params, token, cache, pos_vec, active, seeds, temperature, topp):
            def body(i, carry):
                tok, cache, out = carry
                # per-lane in-block stop: a lane whose window fills mid-
                # block parks itself (writes land in padding, token 0
                # emitted) instead of shrinking the whole batch's block to
                # its remaining space — one near-full lane no longer
                # degrades every concurrent stream to 1-token dispatches
                # (ADVICE r2 #2); callers already deactivate a lane the
                # moment its position cap is reached.
                ok = jnp.logical_and(active, pos_vec + i < seq_len)
                cur = jnp.where(ok, pos_vec + i, park)
                ctx = (
                    jax.default_matmul_precision(precision)
                    if precision
                    else contextlib.nullcontext()
                )
                with ctx:
                    logits, cache = fwd(
                        params, tok, cur, cache,
                        attn_window=window,
                        attn_park_threshold=park, logits_mode="last",
                    )
                last = logits[:, -1, :]
                # per-lane (seed, position)-derived keys: a seeded lane's
                # stream is reproducible independent of the other lanes
                # and of block splits (weak r4 #7 closed for lane mode)
                nxt = _sample_per_lane(last, temperature, topp, seeds, cur)
                nxt = jnp.where(ok, nxt, 0).reshape(-1, 1)
                out = lax.dynamic_update_index_in_dim(out, nxt[:, 0], i, axis=0)
                return nxt, cache, out

            out0 = jnp.zeros((n_steps, token.shape[0]), jnp.int32)
            _, cache, out = lax.fori_loop(
                0, n_steps, body, (token, cache, out0)
            )
            return out, cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            block = block.lower(*self._lane_arg_specs(n_steps)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = block
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return block

    def decode_lanes(
        self,
        tokens: list[int],
        pos: list[int],
        n_steps: int,
        active: list[bool] | None = None,
        temperature: list[float] | None = None,
        topp: list[float] | None = None,
        seeds: list[int | None] | None = None,
    ) -> list[list[int]]:
        """Decode `n_steps` tokens on every ACTIVE lane in one device
        dispatch, each lane at its own position (and its own sampling
        settings — temperature 0 decodes that lane greedily; a per-lane
        `seeds[l]` makes that lane's sampled stream reproducible
        regardless of the other lanes — r4's 'seed ignored in lane mode'
        gap). Returns
        [n_steps][lanes] (parked lanes report token 0). A lane that fills
        its window MID-BLOCK parks itself on device and reports 0 for the
        remaining rows — callers must stop consuming a lane's rows once
        its position cap is reached (both the API scheduler and
        generate_batch already do); the block length is clamped only by
        the DEEPEST live lane, so one near-full lane doesn't reduce the
        whole batch to tiny dispatches."""
        self._require_lanes()
        if len(tokens) != self.batch_size or len(pos) != self.batch_size:
            raise ValueError("tokens/pos must have one entry per lane")
        if active is None:
            active = [True] * self.batch_size
        live = [i for i, a in enumerate(active) if a]
        if not live:
            return []
        n_steps = min(
            n_steps, max(self.header.seq_len - pos[i] for i in live)
        )
        if n_steps <= 0:
            return []
        if temperature is None:
            temperature = [self.temperature] * self.batch_size
        if topp is None:
            topp = [self.sampler.topp] * self.batch_size
        arr = jax.device_put(
            jnp.asarray([[t] for t in tokens], jnp.int32), self._token_sharding
        )
        pos_arr = jnp.asarray(pos, jnp.int32)
        act_arr = jnp.asarray(active, jnp.bool_)
        deepest = max(pos[i] for i in live)
        window = self._attn_window(deepest + n_steps)
        self._note_window(window)
        native = self.kv_native
        block = (
            self._lane_decode_paged_fn(n_steps, window)
            if native
            else self._lane_decode_fn(n_steps, window)
        )
        if (
            self._aot_blocks
            and window < self.header.seq_len
            and deepest + n_steps >= (3 * window) // 4
        ):
            if native:
                self._prefetch(
                    ("lane_block_paged", n_steps, self._attn_window(window + 1)),
                    lambda nw=self._attn_window(window + 1):
                        self._lane_decode_paged_fn(
                            n_steps, nw, origin="prefetch"
                        ),
                )
            else:
                self._prefetch(
                    ("lane_block", n_steps, self._attn_window(window + 1)),
                    lambda nw=self._attn_window(window + 1):
                        self._lane_decode_fn(
                            n_steps, nw, origin="prefetch"
                        ),
                )
        self._rng_calls += 1
        # unseeded lanes draw from an engine-lifetime stream (varies per
        # call); a seeded lane's stream depends ONLY on (its seed, its
        # absolute positions) — reproducible across block splits and
        # independent of other lanes' activity
        seed_vec = [
            (s if s is not None
             else (self._lane_seed_base + 1_000_003 * self._rng_calls + i)
             ) & 0x7FFFFFFF
            for i, s in enumerate(seeds or [None] * self.batch_size)
        ]
        fault = self._fault("decode_lanes")
        if fault is not None and not fault.poison:
            raise fault
        self.recorder.record(
            "step_dispatch", step="decode_lanes", pos=deepest,
            n_steps=n_steps, window=window, n_live=len(live),
        )
        sp = self._spans.begin(
            "decode_lanes", component="engine", n_steps=n_steps,
            pos=deepest, n_live=len(live), window=window,
        )
        t0 = time.perf_counter()
        guard = self._kv_pool_guard if native else self._cache_guard
        with guard():
            if fault is not None:
                raise fault
            if native:
                out, self.kv_pool = block(
                    self.params,
                    arr,
                    self.kv_pool,
                    jnp.asarray(self._page_table),
                    pos_arr,
                    act_arr,
                    jnp.asarray(seed_vec, jnp.int32),
                    jnp.asarray(temperature, jnp.float32),
                    jnp.asarray(topp, jnp.float32),
                )
            else:
                out, self.cache = block(
                    self.params,
                    arr,
                    self.cache,
                    pos_arr,
                    act_arr,
                    jnp.asarray(seed_vec, jnp.int32),
                    jnp.asarray(temperature, jnp.float32),
                    jnp.asarray(topp, jnp.float32),
                )
            # the call above returned as soon as the program was enqueued;
            # the readback is the device-complete wait — split it out so a
            # timeline shows dispatch overhead vs device time
            sp_dev = self._spans.begin(
                "decode_lanes.device", component="engine"
            )
            out_np = np.asarray(out)
            self._spans.end(sp_dev)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="decode_lanes").observe(dt)
        # each active stream advances one token per block row
        self._m_tpot.observe(dt / n_steps)
        self.recorder.record(
            "step_complete", step="decode_lanes", pos=deepest,
            n_steps=n_steps, window=window, n_live=len(live),
            ms=round(dt * 1000, 3),
        )
        return [[int(t) for t in row] for row in out_np]

    def _lane_verify_arg_specs(self, t: int):
        """Arg specs for a speculative verify dispatch (the AOT
        lowering input): token rows are (lanes, 1 + draft bucket) with
        the lane sharding, plus the per-lane position vector and active
        mask; params/cache trees come from the init-time snapshot (same
        no-donated-reads rule as _lane_arg_specs)."""
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, t), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._param_specs,
            tok,
            self._cache_specs,
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.bool_),
        )

    def _lane_verify_fn(
        self, t: int, window: int = 0, origin: str = "dispatch"
    ):
        """Batched draft verification for n-gram speculation
        (runtime/spec.py): a close cousin of _lane_decode_fn that feeds
        each ACTIVE lane a row of [pending token, draft_0..draft_{k-1},
        pads] at vector positions pos..pos+t-1 in ONE forward pass and
        returns the greedy argmax at EVERY position, so the host can
        accept the longest draft prefix the model agrees with plus one
        correction token. Unlike the decode block this is a single fwd
        over t tokens, not t sequential fwds — one weight pass amortized
        over up to t emitted tokens, which is the whole point on an
        HBM-bound decode. Greedy only: sampled lanes take the normal
        decode block in the same scheduler tick. AOT-compiled and
        bucketed by draft length (spec_buckets) so no new shape compiles
        mid-serve; rehearse_admission pre-builds every bucket."""
        key = ("lane_verify", t, window)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        precision = self._precision
        fwd = self._fwd
        park = self._park

        @partial(jax.jit, donate_argnums=(2,))
        def vstep(params, tokens, cache, pos_vec, active):
            cur = jnp.where(active, pos_vec, park)
            ctx = (
                jax.default_matmul_precision(precision)
                if precision
                else contextlib.nullcontext()
            )
            with ctx:
                logits, cache = fwd(
                    params, tokens, cur, cache,
                    attn_window=window, attn_park_threshold=park,
                    logits_mode="all", n_micro=self._pp_micro(t),
                )
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out = jnp.where(active[:, None], out, 0)
            return out, cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            vstep = vstep.lower(*self._lane_verify_arg_specs(t)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = vstep
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return vstep

    def verify_lanes(
        self,
        rows: list[list[int]],
        pos: list[int],
        active: list[bool],
    ) -> list[list[int]]:
        """Verify each active lane's draft row in ONE compiled dispatch.

        `rows[l]` is [pending token, draft_0..draft_{k-1}, zero pads] of
        the shared width t (a 1 + spec bucket); it is fed at positions
        pos[l]..pos[l]+t-1 and the per-position greedy argmax grid
        [lanes][t] comes back (inactive lanes report 0). The caller
        accepts the longest draft prefix matching the argmax of the
        PREVIOUS position plus one correction token and rewinds the
        rest: rejected rows hold garbage KV, but they sit at-or-beyond
        the lane's rewound position, so they are causally masked and
        overwritten before any query can attend to them — the same
        argument that covers block-decode rows past a stop, and why
        publish-on-finish (which covers only history[:pos]) composes
        with rewinds without touching the paged pool's accounting."""
        self._require_lanes()
        if len(rows) != self.batch_size or len(pos) != self.batch_size:
            raise ValueError("rows/pos must have one entry per lane")
        t = len(rows[0])
        if t < 2:
            raise ValueError("verify rows need a pending token + >=1 draft")
        if any(len(r) != t for r in rows):
            raise ValueError("verify rows must share one bucketed width")
        if t > self._lane_pad:
            raise ValueError(
                f"verify width {t} exceeds lane padding {self._lane_pad} "
                "(parked rows would clamp into live cache)"
            )
        live = [i for i, a in enumerate(active) if a]
        if not live:
            return []
        for i in live:
            if pos[i] + t > self.header.seq_len:
                raise ValueError(
                    f"lane {i}: verify row at pos {pos[i]} width {t} "
                    f"exceeds seqLen {self.header.seq_len}"
                )
        deepest = max(pos[i] for i in live)
        window = self._attn_window(deepest + t)
        self._note_window(window)
        native = self.kv_native
        vstep = (
            self._lane_verify_paged_fn(t, window)
            if native
            else self._lane_verify_fn(t, window)
        )
        if (
            self._aot_blocks
            and window < self.header.seq_len
            and deepest + t >= (3 * window) // 4
        ):
            if native:
                self._prefetch(
                    ("lane_verify_paged", t, self._attn_window(window + 1)),
                    lambda nw=self._attn_window(window + 1):
                        self._lane_verify_paged_fn(
                            t, nw, origin="prefetch"
                        ),
                )
            else:
                self._prefetch(
                    ("lane_verify", t, self._attn_window(window + 1)),
                    lambda nw=self._attn_window(window + 1):
                        self._lane_verify_fn(
                            t, nw, origin="prefetch"
                        ),
                )
        arr = jax.device_put(
            jnp.asarray(rows, jnp.int32), self._token_sharding
        )
        pos_arr = jnp.asarray(pos, jnp.int32)
        act_arr = jnp.asarray(active, jnp.bool_)
        fault = self._fault("verify_lanes")
        if fault is not None and not fault.poison:
            raise fault
        self.recorder.record(
            "step_dispatch", step="verify_lanes", pos=deepest,
            t=t, window=window, n_live=len(live),
        )
        sp = self._spans.begin(
            "verify_lanes", component="engine", t=t,
            pos=deepest, n_live=len(live), window=window,
        )
        t0 = time.perf_counter()
        guard = self._kv_pool_guard if native else self._cache_guard
        with guard():
            if fault is not None:
                raise fault
            if native:
                out, self.kv_pool = vstep(
                    self.params, arr, self.kv_pool,
                    jnp.asarray(self._page_table), pos_arr, act_arr,
                )
            else:
                out, self.cache = vstep(
                    self.params, arr, self.cache, pos_arr, act_arr
                )
            sp_dev = self._spans.begin(
                "verify_lanes.device", component="engine"
            )
            out_np = np.asarray(out)
            self._spans.end(sp_dev)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="verify_lanes").observe(dt)
        self.recorder.record(
            "step_complete", step="verify_lanes", pos=deepest,
            t=t, window=window, n_live=len(live),
            ms=round(dt * 1000, 3),
        )
        return [[int(x) for x in row] for row in out_np]

    # -- resident draft model (second-generation speculation) ----------------

    @property
    def has_draft_model(self) -> bool:
        return self._draft_params is not None

    @property
    def draft_seq_len(self) -> int:
        """The draft checkpoint's own context length — the scheduler must
        not request model drafts for a lane past this position (the tiny
        checkpoint may carry a shorter seqLen than the target)."""
        return self._draft_header.seq_len if self._draft_header else 0

    def init_draft_model(self, model_path: str) -> None:
        """Load a tiny Llama-family DRAFT checkpoint into the same engine
        (``--speculation draft``, runtime/spec.py): its params live
        beside the target's on the same mesh, its KV cache mirrors the
        lane layout (own seqLen + the same padding rows), and its
        programs (``draft_prefill`` chunk buckets, ``draft_step`` greedy
        k-step blocks) go through the SAME _compile_lock/_inflight/
        rehearse machinery as every serving program — AOT-compiled,
        xlalint-checked, cost-budgeted. The draft must share the
        target's tokenizer, which structurally means its vocab: drafts
        are proposed as target token ids and verified by the target, so
        a vocab mismatch is a config error, not a quality problem."""
        self._require_lanes()
        if self.pp > 1 or self.sp > 1:
            raise ValueError(
                "draft model requires pp == 1 and sp == 1 (the draft "
                "forward runs on the flat mesh path)"
            )
        reader = ModelReader(model_path, max_seq_len=self.header.seq_len)
        dh = reader.header
        if dh.vocab_size != self.header.vocab_size:
            raise ValueError(
                f"draft model vocab {dh.vocab_size} != target vocab "
                f"{self.header.vocab_size}; the draft must share the "
                "target's tokenizer"
            )
        validate_tp(dh, self.tp)
        # dense weights: the draft is tiny, so the q40 device formats'
        # divisibility constraints and kernel launches buy nothing here
        self._draft_params = load_params(
            reader,
            dtype=self.dtype,
            put=shard_params_put(self.mesh, dh),
            weight_format="dense",
            fuse=0,
        )
        self._draft_header = dh
        self._draft_cache_sharding = {
            k: NamedSharding(self.mesh, spec)
            for k, spec in cache_specs(dh, sp=False, pp=False).items()
        }
        mesh = self.mesh

        def dfwd(params, tokens, pos, cache, *, attn_park_threshold=0,
                 logits_mode="all"):
            return forward(
                params, dh, tokens, pos, cache, mesh=mesh,
                attn_window=0, logits_mode=logits_mode,
                attn_park_threshold=attn_park_threshold,
            )

        self._draft_fwd = dfwd
        self.draft_cache = self._fresh_draft_cache()
        self._draft_param_specs = jax.tree.map(_sds, self._draft_params)
        self._draft_cache_specs = jax.tree.map(_sds, self.draft_cache)
        self._m_spec_draft_ms = self.obs.histogram(
            "dllama_spec_draft_model_step_ms",
            "Wall milliseconds of one draft-model dispatch (catch-up "
            "prefill chunk or k-step propose block).",
            labelnames=("kind",),
            buckets=(0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000),
        )
        self.recorder.record(
            "draft_model_loaded", path=model_path, seq_len=dh.seq_len,
            vocab=dh.vocab_size,
        )

    def _require_draft_model(self) -> None:
        if self._draft_params is None:
            raise ValueError("draft model not loaded (init_draft_model)")

    def _fresh_draft_cache(self):
        """Rebuild the draft KV cache; bumps draft_cache_epoch so the
        scheduler knows every lane's draft context is gone (its
        _draft_pos map resets and catch-up prefill re-derives it —
        advisory state only: drafts are always verified by the target,
        so a dropped draft cache costs acceptance, never bytes)."""
        self.draft_cache_epoch += 1
        self.recorder.record(
            "draft_cache_epoch", epoch=self.draft_cache_epoch
        )
        dh = self._draft_header
        cache = init_kv_cache(
            dh,
            self.batch_size,
            dtype=self.dtype,
            seq_len=dh.seq_len + self._lane_pad,
        )
        return {
            k: jax.device_put(v, self._draft_cache_sharding[k])
            for k, v in cache.items()
        }

    @contextlib.contextmanager
    def _draft_cache_guard(self):
        """_cache_guard's draft twin: draft programs donate
        ``self.draft_cache``, so a failed dispatch rebuilds it before
        re-raising. The target cache is untouched — a draft-side crash
        never costs a live conversation its context."""
        try:
            yield
        except BaseException as e:
            self.recorder.record(
                "error", error=str(e), error_type=type(e).__name__,
                draft=True,
            )
            try:
                self.draft_cache = self._fresh_draft_cache()
            except Exception as rebuild_err:  # pragma: no cover
                raise rebuild_err from e
            raise

    def _draft_park(self) -> int:
        return self._draft_header.seq_len  # first draft padding row

    def _draft_bucket_for(self, n: int, pos: int) -> int:
        """_bucket_for against the DRAFT sequence length (the draft
        checkpoint may be shorter than the target)."""
        space = self._draft_header.seq_len - pos
        fitting = [b for b in self.prefill_buckets if b <= space]
        if not fitting:
            return max(min(space, n), 1)
        for b in fitting:
            if n <= b:
                return b
        return fitting[-1]

    def _draft_prefill_arg_specs(self, t: int):
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, t), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._draft_param_specs,
            tok,
            self._draft_cache_specs,
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )

    def _draft_prefill_fn(self, t: int, origin: str = "dispatch"):
        """Draft-cache catch-up prefill: one lane writes a chunk at its
        own position, every other lane parks in the draft padding rows —
        _lane_prefill_fn against the draft params/cache. Full attention
        reads (window 0): the draft is small enough that windowing buys
        nothing over its whole seqLen."""
        key = ("draft_prefill", t)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        self._require_draft_model()
        dfwd = self._draft_fwd
        park = self._draft_park()

        @partial(jax.jit, donate_argnums=(2,))
        def step(params, tokens, cache, pos_vec):
            _, cache = dfwd(
                params, tokens, pos_vec, cache,
                attn_park_threshold=park, logits_mode="last",
            )
            return cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            step = step.lower(*self._draft_prefill_arg_specs(t)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = step
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return step

    def _draft_step_arg_specs(self, n_steps: int):
        b = self.batch_size
        tok = jax.ShapeDtypeStruct(
            (b, 1), jnp.int32, sharding=self._token_sharding
        )
        return (
            self._draft_param_specs,
            tok,
            self._draft_cache_specs,
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.bool_),
        )

    def _draft_step_fn(self, n_steps: int, origin: str = "dispatch"):
        """Greedy k-step draft-model block: _lane_decode_fn's shape
        (per-lane positions, parked inactive lanes, fori_loop feed-back)
        minus sampling — drafts only ever seed a greedy verify, so plain
        argmax is the whole sampler. One host dispatch proposes k tokens
        for every drafting lane at once."""
        key = ("draft_step", n_steps)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            ev = self._inflight.get(key) if origin == "dispatch" else None
        if ev is not None:
            ev.wait()
            with self._compile_lock:
                if key in self._compiled:
                    return self._compiled[key]
        self._require_draft_model()
        dfwd = self._draft_fwd
        park = self._draft_park()
        dseq = self._draft_header.seq_len

        @partial(jax.jit, donate_argnums=(2,))
        def block(params, token, cache, pos_vec, active):
            def body(i, carry):
                tok, cache, out = carry
                ok = jnp.logical_and(active, pos_vec + i < dseq)
                cur = jnp.where(ok, pos_vec + i, park)
                logits, cache = dfwd(
                    params, tok, cur, cache,
                    attn_park_threshold=park, logits_mode="last",
                )
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
                nxt = jnp.where(ok, nxt, 0).reshape(-1, 1)
                out = lax.dynamic_update_index_in_dim(
                    out, nxt[:, 0], i, axis=0
                )
                return nxt, cache, out

            out0 = jnp.zeros((n_steps, token.shape[0]), jnp.int32)
            _, cache, out = lax.fori_loop(
                0, n_steps, body, (token, cache, out0)
            )
            return out, cache

        self.recorder.record("compile_start", key=str(key), origin=origin)
        t0 = time.perf_counter()
        if self._aot_blocks:
            block = block.lower(*self._draft_step_arg_specs(n_steps)).compile()
        dt = time.perf_counter() - t0
        with self._compile_lock:
            self._compiled[key] = block
            self._compile_origin[key] = origin
            if self._aot_blocks:
                self._compile_seconds[key] = dt
        self._m_compiles.labels(origin=origin).inc()
        self.recorder.record(
            "compile_end", key=str(key), origin=origin, s=round(dt, 4)
        )
        self._xlalint_after_compile(key)
        return block

    def draft_prefill(self, lane: int, tokens: list[int], pos0: int) -> None:
        """Catch the draft cache up on `lane`: write `tokens` (context
        rows the draft has not seen — typically the tail the target
        accepted since the last model draft) at pos0.., chunked through
        the bucketed draft_prefill programs. Rows past a later rewind
        are overwritten by the next catch-up before any draft query can
        attend to them — the same causal-mask argument that makes the
        target's verify rewind safe."""
        self._require_draft_model()
        if not 0 <= lane < self.batch_size:
            raise ValueError(f"lane {lane} out of range")
        n = len(tokens)
        if n < 1:
            return
        dseq = self._draft_header.seq_len
        if pos0 + n > dseq:
            raise ValueError(
                f"{n} draft fill tokens at pos {pos0} exceed draft "
                f"seqLen {dseq}"
            )
        park = self._draft_park()
        fills = list(tokens)
        p = pos0
        t0 = time.perf_counter()
        while fills:
            bucket = self._draft_bucket_for(len(fills), p)
            width = min(bucket, len(fills))
            chunk = fills[:width] + [0] * (bucket - width)
            rows = [[0] * bucket for _ in range(self.batch_size)]
            rows[lane] = chunk
            posv = [park] * self.batch_size
            posv[lane] = p
            step = self._draft_prefill_fn(bucket)
            arr = jax.device_put(
                jnp.asarray(rows, jnp.int32), self._token_sharding
            )
            with self._draft_cache_guard():
                self.draft_cache = step(
                    self._draft_params, arr, self.draft_cache,
                    jnp.asarray(posv, jnp.int32),
                )
            fills = fills[width:]
            p += width
        dt = time.perf_counter() - t0
        self._m_step.labels(kind="draft_prefill").observe(dt)
        if self._m_spec_draft_ms is not None:
            self._m_spec_draft_ms.labels(kind="prefill").observe(dt * 1000)
        self.recorder.record(
            "step_complete", step="draft_prefill", lane=lane, pos=pos0,
            n_tokens=n, ms=round(dt * 1000, 3),
        )

    def draft_propose(
        self,
        tokens: list[int],
        pos: list[int],
        active: list[bool],
        k: int,
    ) -> list[list[int]]:
        """Propose up to `k` greedy draft-model tokens per ACTIVE lane in
        one dispatch: lane l feeds tokens[l] at pos[l] and autoregresses
        k steps through the draft. Returns [lanes][k] (inactive or
        past-draft-capacity rows report 0). Purely advisory — every
        returned token goes through the target's verify pass, so this
        can be wrong, stale, or truncated without any correctness
        cost."""
        self._require_draft_model()
        if len(tokens) != self.batch_size or len(pos) != self.batch_size:
            raise ValueError("tokens/pos must have one entry per lane")
        live = [i for i, a in enumerate(active) if a]
        if not live or k < 1:
            return []
        dseq = self._draft_header.seq_len
        k = min(k, max(dseq - pos[i] for i in live))
        if k <= 0:
            return []
        block = self._draft_step_fn(k)
        arr = jax.device_put(
            jnp.asarray([[t] for t in tokens], jnp.int32),
            self._token_sharding,
        )
        self.recorder.record(
            "step_dispatch", step="draft_step", n_steps=k,
            n_live=len(live),
        )
        sp = self._spans.begin(
            "draft_step", component="engine", n_steps=k, n_live=len(live),
        )
        t0 = time.perf_counter()
        with self._draft_cache_guard():
            out, self.draft_cache = block(
                self._draft_params, arr, self.draft_cache,
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(active, jnp.bool_),
            )
            out_np = np.asarray(out)
        dt = time.perf_counter() - t0
        self._spans.end(sp)
        self._m_step.labels(kind="draft_step").observe(dt)
        if self._m_spec_draft_ms is not None:
            self._m_spec_draft_ms.labels(kind="propose").observe(dt * 1000)
        self.recorder.record(
            "step_complete", step="draft_step", n_steps=k,
            n_live=len(live), ms=round(dt * 1000, 3),
        )
        # transpose [k][lanes] -> [lanes][k]
        return [
            [int(out_np[i][lane]) for i in range(k)]
            for lane in range(self.batch_size)
        ]

    def _bucket_for(self, n: int, pos: int) -> int:
        """Smallest bucket covering n tokens whose PADDED extent still fits
        in the cache (dynamic_update_slice clamps silently if pos+bucket >
        seqLen, which would corrupt earlier cache rows)."""
        space = self.header.seq_len - pos
        if self.pp > 1 and self.sp > 1:
            # stage-local sp writes are windowed per shard (run_layers
            # sp_axis): no chunk may exceed one shard's local rows. The
            # bucket filter enforces this for configured buckets; cap the
            # fallback widths below the same way.
            space = min(space, self.header.seq_len // self.sp)
        fitting = [b for b in self.prefill_buckets if b <= space]
        if not fitting:
            # guarded by the prefill bounds check: space >= 1 and bucket 1
            # may not be configured; fall back to exact width. Under sp a
            # chunk wider than 1 shards its query axis over sp chips, so
            # round down to a shardable width (width-1 chunks go through
            # the merged-stats branch instead and are always valid).
            if self.sp > 1 and space % self.sp:
                space -= space % self.sp
                if space == 0:
                    return 1
            return max(space, 1)
        for b in fitting:
            if n <= b:
                return b
        return fitting[-1]

    # -- public API ----------------------------------------------------------

    def prefill(self, tokens: list[int], pos: int = 0) -> StepStats:
        """Run all but the last prompt token through the cache (the last
        token is the decode loop's first input, reference: dllama.cpp:38-68)."""
        return self._prefill_rows([tokens] * self.batch_size, pos)

    def _prefill_rows(self, rows: list[list[int]], pos: int = 0) -> StepStats:
        """Chunked, bucketed prefill of per-lane token rows (all the same
        length); everything but the last token of each row enters the cache."""
        n = len(rows[0])
        if n < 1:
            raise ValueError("empty prompt")
        if pos + n - 1 > self.header.seq_len:
            # dynamic_update_slice clamps silently; fail loudly instead
            # (the reference bounds pos by seqLen the same way,
            # dllama.cpp:27-28,76).
            raise ValueError(
                f"prompt of {n} tokens at pos {pos} exceeds "
                f"seqLen {self.header.seq_len}"
            )
        fills = [row[:-1] for row in rows]
        total_ms = 0.0
        p = pos
        while fills[0]:
            bucket = self._bucket_for(len(fills[0]), p)
            width = min(bucket, len(fills[0]))
            padded = [
                fill[:width] + [0] * (bucket - width) for fill in fills
            ]
            fills = [fill[width:] for fill in fills]
            arr = jnp.asarray(padded, dtype=jnp.int32)
            arr = jax.device_put(arr, self._token_sharding)
            window = self._attn_window(p + bucket)
            step = self._step_fn(bucket, greedy=False, window=window)
            self.recorder.record(
                "step_dispatch", step="prefill", pos=p,
                bucket=bucket, window=window,
            )
            sp = self._spans.begin(
                "prefill", component="engine", pos=p, bucket=bucket,
            )
            t0 = time.perf_counter()
            # Padding tokens write garbage into cache slots [p+width,
            # p+bucket) — harmless: the causal mask hides them until real
            # tokens overwrite those positions.
            with self._cache_guard():
                _, self.cache = step(
                    self.params, arr, self.cache, jnp.int32(p)
                )
                # scalar readback: a real sync (block_until_ready returns
                # early on the tunneled axon TPU platform)
                ck = self.cache["k"]
                ck = ck.q if hasattr(ck, "q") else ck
                np.asarray(jax.device_get(ck[0, 0, 0, 0, 0]))
            chunk_ms = (time.perf_counter() - t0) * 1000
            self._spans.end(sp)
            total_ms += chunk_ms
            self.recorder.record(
                "step_complete", step="prefill", pos=p,
                bucket=bucket, window=window, ms=round(chunk_ms, 3),
            )
            p += width
        return StepStats(time_ms=total_ms, n_tokens=max(n - 1, 0))

    def _block_width(self, pos: int, block: int) -> int:
        """Block size to run at `pos`: the full compiled width whenever it
        fits the cache, else the exact remaining space."""
        if pos + block <= self.header.seq_len:
            return block
        return self.header.seq_len - pos

    def decode_step(self, token: int, pos: int) -> tuple[int, StepStats]:
        """One decode step: feed `token` at `pos`, return the sampled next
        token (reference: dllama.cpp:74-99)."""
        if pos >= self.header.seq_len:
            raise ValueError(
                f"decode position {pos} out of range (seqLen "
                f"{self.header.seq_len}); the KV cache would clamp silently"
            )
        arr = jnp.asarray([[token]] * self.batch_size, dtype=jnp.int32)
        arr = jax.device_put(arr, self._token_sharding)
        greedy = self.temperature == 0.0
        window = self._attn_window(pos + 1)
        step = self._step_fn(1, greedy=greedy, window=window)
        self.recorder.record(
            "step_dispatch", step="decode_step", pos=pos, window=window
        )
        sp = self._spans.begin(
            "decode_step", component="engine", pos=pos, window=window
        )
        t0 = time.perf_counter()
        with self._cache_guard():
            out, self.cache = step(self.params, arr, self.cache, jnp.int32(pos))
            out = jax.block_until_ready(out)
        ms = (time.perf_counter() - t0) * 1000
        self._spans.end(sp)
        self.recorder.record(
            "step_complete", step="decode_step", pos=pos, window=window,
            ms=round(ms, 3),
        )
        if greedy:
            next_token = int(np.asarray(out)[0])
        else:
            # the one host-side sampling site left (block decode samples
            # on-device inside the compiled program)
            with self._spans.span("sample", component="engine", pos=pos):
                next_token = self.sampler.sample(np.asarray(out)[0])
        return next_token, StepStats(time_ms=ms, n_tokens=1)

    def generate(
        self,
        prompt_tokens: list[int],
        max_steps: int,
        on_token=None,
        stop_condition=None,
        block_size: int = 8,
        start_pos: int = 0,
    ):
        """Prefill + decode loop. Yields nothing; returns (tokens, eval_stats,
        pred_stats). `on_token(token)` fires per generated token and may
        return False to stop (EOS handling lives with the caller, which owns
        the tokenizer/EosDetector).

        Greedy decoding runs in on-device blocks of `block_size` tokens
        (one host dispatch per block); a stop mid-block leaves the already-
        written KV rows beyond the stop as garbage, which is safe — they
        are causally masked and overwritten by the next prefill at those
        positions."""
        # max_steps counts positions from start_pos (for start_pos == 0 this
        # is the reference's absolute --steps semantics, dllama.cpp:76)
        max_pos = min(self.header.seq_len, start_pos + max_steps)
        eval_stats = self.prefill(prompt_tokens, pos=start_pos)
        pos = start_pos + len(prompt_tokens) - 1
        token = prompt_tokens[-1]
        out_tokens: list[int] = []
        pred_ms = 0.0
        block = max(1, block_size)
        stopped = False
        while pos < max_pos and not stopped:
            if block > 1:
                # run the full block size whenever it fits in the cache
                # (compiling a one-off program per tail length costs seconds
                # on this platform); surplus tokens are simply not consumed
                n = self._block_width(pos, block)
                want = min(n, max_pos - pos)
                t0 = time.perf_counter()
                toks = self.decode_block(token, pos, n)[:want]
                pred_ms += (time.perf_counter() - t0) * 1000
                if not toks:
                    break
                for tk in toks:
                    pos += 1
                    out_tokens.append(tk)
                    if on_token is not None and on_token(tk) is False:
                        stopped = True
                        break
                    if stop_condition is not None and stop_condition(tk):
                        stopped = True
                        break
                token = out_tokens[-1]
            else:
                token, stats = self.decode_step(token, pos)
                pred_ms += stats.time_ms
                pos += 1
                out_tokens.append(token)
                if on_token is not None and on_token(token) is False:
                    break
                if stop_condition is not None and stop_condition(token):
                    break
        return out_tokens, eval_stats, StepStats(pred_ms, len(out_tokens))

    def generate_batch(
        self,
        prompts: list[list[int]],
        max_steps: int,
        block_size: int = 8,
    ) -> list[list[int]]:
        """Decode independent sequences, one per batch lane (requires
        batch_size == len(prompts)). Prompts may have DIFFERENT lengths:
        each lane prefills separately (parked writes keep the others
        intact) and decodes from its own position; `max_steps` is the
        per-lane absolute position cap, matching `generate`. Greedy/
        sampled per the engine temperature; returns per-lane token
        lists."""
        if len(prompts) != self.batch_size:
            raise ValueError(
                f"{len(prompts)} prompts for batch_size {self.batch_size}"
            )
        n = len(prompts[0])
        max_pos = min(self.header.seq_len, max_steps)
        if all(len(p) == n for p in prompts):
            # synchronized fast path: one batched prefill, shared positions
            self._prefill_rows(prompts, 0)
            pos = n - 1
            tokens = [p[-1] for p in prompts]
            outs: list[list[int]] = [[] for _ in prompts]
            while pos < max_pos:
                nb = self._block_width(pos, block_size)
                want = min(nb, max_pos - pos)
                rows = self.decode_block(tokens, pos, nb)[:want]
                if not rows:
                    break
                for row in rows:
                    for lane, t in enumerate(row):
                        outs[lane].append(t)
                tokens = rows[-1]
                pos += len(rows)
            return outs

        self._require_lanes()
        for lane, p in enumerate(prompts):
            if not p:
                raise ValueError(f"lane {lane}: empty prompt")
            self.prefill_lane(lane, p)
        pos = [len(p) - 1 for p in prompts]
        tokens = [p[-1] for p in prompts]
        active = [pos[i] < max_pos for i in range(self.batch_size)]
        outs = [[] for _ in prompts]
        while any(active):
            rows = self.decode_lanes(tokens, pos, block_size, active)
            if not rows:
                break
            for row in rows:
                for lane, t in enumerate(row):
                    if active[lane]:
                        outs[lane].append(t)
                        pos[lane] += 1
                        tokens[lane] = t
                        if pos[lane] >= max_pos:
                            active[lane] = False
        return outs

    # -- introspection (obs) -------------------------------------------------

    @staticmethod
    def _key_kind(key) -> str:
        """Step kind of a compile-cache key, matching the
        `dllama_engine_step_seconds{kind=}` label values where one exists."""
        if isinstance(key, tuple) and key and isinstance(key[0], str):
            return {
                "block": "decode_block",
                "lane_block": "decode_lanes",
                "lane_prefill": "prefill_lane",
                "lane_verify": "verify_lanes",
                # pool-native paged variants observe into the same step
                # kinds as their slab twins — serving dashboards don't
                # care which KV home a block decoded from
                "lane_block_paged": "decode_lanes",
                "lane_prefill_paged": "prefill_lane",
                "lane_verify_paged": "verify_lanes",
                "kv_page_copy": "kv_page_copy",
                "score": "score",
            }.get(key[0], key[0])
        return "prefill"  # plain (t, greedy, window) keys

    def compile_cache_report(self) -> list[dict]:
        """Per-key view of the compile cache (what `/v1/debug/compile`
        serves): the cache key, its step kind, who built it
        (dispatch/prefetch), the AOT build wall seconds where measured,
        and XLA's cost analysis — or the explicit ``"unavailable"``
        marker for lazily jitted programs, which expose no executable
        until their first call."""
        from ..obs.cost import extract_cost

        with self._compile_lock:
            items = list(self._compiled.items())
            origins = dict(self._compile_origin)
            seconds = dict(self._compile_seconds)
        out = []
        for key, fn in items:
            cost = self._cost_cache.get(key)
            if cost is None:
                cost = extract_cost(fn)
                if cost is not None:
                    self._cost_cache[key] = cost
            out.append(
                {
                    "key": list(key),
                    "kind": self._key_kind(key),
                    "origin": origins.get(key, "dispatch"),
                    "compile_seconds": seconds.get(key),
                    "cost": cost if cost is not None else "unavailable",
                }
            )
        return out

    def cost_report(self) -> dict:
        """Fold the compile cache into per-kind cost gauges and an
        achieved-vs-roofline fraction from the measured step histograms.

        The representative program per kind is the one accessing the most
        bytes (the widest attention window — what bounds steady-state
        decode); its roofline fraction divides achieved bytes/s
        (cost-analysis bytes / mean measured step seconds) by the chip's
        HBM peak. Fractions are absent when the backend's peak is unknown
        (CPU) or the kind has no measured steps yet."""
        from ..obs.cost import hbm_peak_bytes_per_s, roofline_fraction

        g_flops = self.obs.gauge(
            "dllama_compiled_step_flops",
            "XLA cost-analysis flops of the representative (most "
            "bytes-accessed) compiled program, per step kind.",
            labelnames=("kind",),
        )
        g_bytes = self.obs.gauge(
            "dllama_compiled_step_bytes_accessed",
            "XLA cost-analysis bytes accessed of the representative "
            "compiled program, per step kind.",
            labelnames=("kind",),
        )
        g_roof = self.obs.gauge(
            "dllama_step_roofline_fraction",
            "Achieved HBM bandwidth (cost-analysis bytes / mean measured "
            "step seconds) over the chip's peak, per step kind; only set "
            "when both a cost and a known peak exist.",
            labelnames=("kind",),
        )
        peak = hbm_peak_bytes_per_s()
        per_kind: dict[str, dict] = {}
        for e in self.compile_cache_report():
            cost = e["cost"]
            if not isinstance(cost, dict):
                continue
            cur = per_kind.get(e["kind"])
            if cur is None or cost["bytes_accessed"] > cur["bytes_accessed"]:
                per_kind[e["kind"]] = {
                    "key": e["key"],
                    "flops": cost["flops"],
                    "bytes_accessed": cost["bytes_accessed"],
                }
        for kind, info in per_kind.items():
            g_flops.labels(kind=kind).set(info["flops"])
            g_bytes.labels(kind=kind).set(info["bytes_accessed"])
            hist = self._m_step.labels(kind=kind)
            mean_s = (hist.sum / hist.count) if hist.count else 0.0
            info["mean_step_s"] = mean_s if mean_s > 0 else None
            frac = roofline_fraction(info["bytes_accessed"], mean_s, peak)
            info["roofline_fraction"] = frac
            if frac is not None:
                g_roof.labels(kind=kind).set(frac)
        return {"hbm_peak_bytes_per_s": peak, "kinds": per_kind}

    def occupancy(self) -> dict:
        """The engine's static contribution to an admission-control
        occupancy snapshot (runtime/admission.py): lane capacity and the
        measured per-kind step-time p50s the LoadPredictor forecasts
        from. The scheduler overlays the dynamic half (active lanes,
        parked streams, queue depth) under its own lock."""
        step_p50_s: dict[str, float] = {}
        for kind in ("decode_lanes", "prefill_lane_chunk", "verify_lanes"):
            try:
                p50 = self._m_step.labels(kind=kind).percentile(0.5)
            except Exception:
                p50 = None
            if p50 is not None:
                step_p50_s[kind] = p50
        return {
            "lanes_total": self.batch_size,
            "prefill_buckets": list(self.prefill_buckets),
            "step_p50_s": step_p50_s,
        }

    def _xlalint_baseline_set(self) -> set:
        if self._xlalint_baseline is None:
            from ..analysis.core import load_baseline
            from ..analysis.xlalint import default_baseline_path

            self._xlalint_baseline = load_baseline(default_baseline_path())
        return self._xlalint_baseline

    def _xlalint_after_compile(self, key) -> None:
        """Lint ONE just-compiled program (called at the end of every
        builder fn, so dispatch compiles, window prefetches, and
        rehearse_admission all pass through). Warn-by-default;
        DLLAMA_XLALINT=strict raises XlalintError, =0/off disables.
        Lint bugs themselves must never take down a serving compile, so
        non-strict mode swallows analysis errors after logging them."""
        if self._xlalint_mode in ("0", "off", "false"):
            return
        if not self._aot_blocks:
            return  # lazily jitted: no executable to read yet
        import logging

        from ..analysis.xlalint import XlalintError, lint_engine_key

        try:
            new = lint_engine_key(self, key, self._xlalint_baseline_set())
        except Exception:
            logging.getLogger(__name__).exception(
                "xlalint failed analyzing %r (program NOT checked)", key
            )
            return
        if not new:
            return
        rendered = "; ".join(f.render() for f in new)
        self._m_xlalint.inc(len(new))
        if self._xlalint_mode == "strict":
            raise XlalintError(
                f"xlalint: {len(new)} new finding(s) in compiled program "
                f"{key!r}: {rendered}"
            )
        logging.getLogger(__name__).warning(
            "xlalint: %d new finding(s) in compiled program %r: %s",
            len(new), key, rendered,
        )

    def xlalint_report(self) -> dict:
        """Compiled-program lint over the WHOLE compile cache (what
        `GET /v1/debug/xlalint` serves): per-program census, findings
        split new-vs-baselined against xlalint-baseline.json, and the
        keys skipped for exposing no executable. See
        docs/static_analysis.md."""
        from ..analysis.xlalint import lint_engine_report

        rep = lint_engine_report(self, self._xlalint_baseline_set())
        rep["mode"] = self._xlalint_mode
        return rep

