"""Observability: memory reports, ICI traffic estimates, profiler hooks.

The reference's observability surface (SURVEY.md §5) is: required-memory
printout at startup (nn-core.cpp:175-189), per-token Eval/Sync ms +
Sent/Recv kB (dllama.cpp:59-66), and compile-time debug dumps. The TPU
equivalents here:

  * `memory_report` — exact per-leaf accounting of params + KV cache bytes,
    total and per-chip (what the reference's `printRequiredMemory` did);
  * `ici_traffic_per_token` — analytic bytes/token of tensor-parallel
    collectives (the Sent/Recv column: ICI traffic isn't countable from the
    host the way the reference counts socket bytes, but it is exactly
    determined by the sharding layout);
  * `profile` — context manager around jax.profiler for kernel-level traces
    (the deep-dive tool the reference lacked entirely).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

import jax
import numpy as np

from ..formats.model_file import LlmHeader


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GB", 1024**3), ("MB", 1024**2), ("kB", 1024)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def _leaf_bytes(tree) -> int:
    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "dtype")
    )


@dataclass
class MemoryReport:
    params_bytes: int
    cache_bytes: int
    n_devices: int
    replicated_bytes: int = 0
    tp_sharded_bytes: int = 0  # embed: split over tp, replicated elsewhere
    tp: int = 1

    @property
    def total_bytes(self) -> int:
        return self.params_bytes + self.cache_bytes

    @property
    def per_device_bytes(self) -> int:
        # replicated leaves (norms, rope) live whole on every chip; the
        # embed table splits over tp ONLY (P("tp", None)) and is
        # replicated across the remaining mesh axes; everything else
        # divides by the full mesh size
        n = max(self.n_devices, 1)
        tp = max(self.tp, 1)
        sharded = self.total_bytes - self.replicated_bytes - self.tp_sharded_bytes
        return (
            self.replicated_bytes
            + self.tp_sharded_bytes // tp
            + sharded // n
        )

    def print(self) -> None:
        print(f"💾 Params: {_fmt_bytes(self.params_bytes)}")
        print(f"💾 KV cache: {_fmt_bytes(self.cache_bytes)}")
        print(
            f"💾 Total: {_fmt_bytes(self.total_bytes)} "
            f"(~{_fmt_bytes(self.per_device_bytes)}/chip over "
            f"{self.n_devices} chip(s))"
        )


_REPLICATED_KEYS = {
    # embed left this set in r5: vocab-sharded over tp (param_spec_tree)
    "final_norm", "rope_cos", "rope_sin",
    "att_norm", "ffn_norm", "q_norm", "k_norm", "moe_gate",
}


def memory_report(params, cache, n_devices: int = 1, tp: int = 1) -> MemoryReport:
    """Accounting of the loaded model (reference: printRequiredMemory).
    Replication follows parallel/sharding.param_spec_tree: norms, gates
    and rope tables are whole on every chip; the embed table splits over
    `tp` (vocab-sharded, r5) and is replicated across the other axes."""
    replicated = 0
    for key in _REPLICATED_KEYS:
        for scope in (params, params.get("layers", {})):
            leaf = scope.get(key) if hasattr(scope, "get") else None
            if leaf is not None:
                replicated += _leaf_bytes(leaf)
    return MemoryReport(
        params_bytes=_leaf_bytes(params),
        cache_bytes=_leaf_bytes(cache),
        n_devices=n_devices,
        replicated_bytes=replicated,
        tp_sharded_bytes=_leaf_bytes(params.get("embed")),
        tp=tp,
    )


def ici_traffic_per_token(
    h: LlmHeader, tp: int, activation_bytes: float = 2.0,
    include_logits: bool = True, pp: int = 1,
    pp_activation_bytes: float | None = None,
) -> int:
    """Analytic per-decoded-token ICI bytes per chip for the TP/PP layout.

    TP: two all-reduces of a [dim] activation per layer (after attention's
    col-split wo and the FFN's col-split w2 — where the reference ran
    SYNC_NODE_SLICES + MERGE_ADD, llm.cpp:403,554) plus the logits
    all-gather (vocab/tp per chip receives the rest). Ring all-reduce moves
    2*(tp-1)/tp of the payload per chip. `activation_bytes`: 4 for the
    f32 psum payload, 1.125 for Q80-compressed sync
    (buffer_float_type="q80", parallel/collectives.psum_q80 — the
    reference's README.md:89 ~26% figure), 2 for bf16 GSPMD all-reduces.

    PP: one [dim] activation ppermute per pipeline tick (pp ticks per
    decode token, parallel/pipeline.forward_pp) plus the exit-register
    all-reduce — tiny next to the tp terms, listed for honesty. These
    hand-offs carry UNCOMPRESSED activations (the stage register's model
    dtype), so they get their own `pp_activation_bytes` (defaults to
    `activation_bytes`) — Q80 sync compression applies only to the tp
    partial-sum psums, never to the pipeline hops.
    """
    total = 0.0
    if tp > 1:
        ring = 2 * (tp - 1) / tp
        total += h.n_layers * 2 * h.dim * activation_bytes * ring
        # vocab-sharded embedding (r5): one [dim] psum assembling the
        # looked-up row — same payload class as a layer psum
        total += h.dim * activation_bytes * ring
        if include_logits:
            total += h.vocab_size * 4 * (tp - 1) / tp
    if pp > 1:
        ppb = activation_bytes if pp_activation_bytes is None else pp_activation_bytes
        total += pp * h.dim * ppb  # tick hand-offs
        total += 2 * (pp - 1) / pp * h.dim * ppb  # exit psum
    return int(total)


_COLLECTIVE_MARKERS = (
    "all-reduce", "allreduce", "all-gather", "allgather", "reduce-scatter",
    "reducescatter", "collective-permute", "collectivepermute", "all-to-all",
    "alltoall",
)


def measure_sync_ms(run_fn, steps: int = 3) -> float | None:
    """MEASURED per-call collective (sync) wall time — the counterpart of
    the reference's per-step sync clock (src/nn/nn-executor.cpp:158-163,
    printed per token by dllama.cpp:59-66). The reference wraps its
    socket waits in a timer; under XLA the collectives are fused into the
    compiled program, so the measurement comes from the profiler instead:
    run `run_fn()` `steps` times under `jax.profiler.trace`, parse the
    perfetto trace, and sum the durations of collective HLO events
    (all-reduce / all-gather / reduce-scatter / collective-permute /
    all-to-all) across device lanes, averaged over devices and calls.

    Returns ms per call per device, or None when the profile contains no
    trace (profiler unavailable). `run_fn` must block until the step
    really finished (readback), and must be IDEMPOTENT on engine state —
    callers re-run the upcoming step at a fixed position (rewriting the
    same KV rows), so the measurement does not perturb the stream."""
    import glob
    import gzip
    import json
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        try:
            with jax.profiler.trace(d):
                for _ in range(steps):
                    run_fn()
        except Exception:
            return None
        files = glob.glob(
            os.path.join(d, "**", "*.trace.json.gz"), recursive=True
        )
        total_us = 0.0
        pids = set()
        found = False
        for f in files:
            try:
                with gzip.open(f, "rt") as fh:
                    trace = json.load(fh)
            except Exception:
                continue
            for ev in trace.get("traceEvents", []):
                if ev.get("ph") != "X":
                    continue
                found = True
                name = str(ev.get("name", "")).lower()
                if any(m in name for m in _COLLECTIVE_MARKERS):
                    total_us += float(ev.get("dur", 0.0))
                    pids.add(ev.get("pid", 0))
        if not found:
            return None
        n_lanes = max(len(pids), 1)
        return total_us / 1000.0 / steps / n_lanes


@contextlib.contextmanager
def profile(log_dir: str | None):
    """jax.profiler trace scope; no-op when log_dir is falsy.

    Profiler failures degrade to a logged warning instead of killing the
    run: start_trace raises on a double-start (another profiler session
    alive in the process) and some backends lack the profiler service
    entirely — neither should take down the generation being profiled."""
    if not log_dir:
        yield
        return
    import logging

    log = logging.getLogger(__name__)
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:
        log.warning(
            "jax.profiler.start_trace(%r) failed (already tracing, or "
            "profiler unavailable on this backend); continuing unprofiled",
            log_dir,
            exc_info=True,
        )
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                print(f"🔬 Profile trace written to {log_dir}")
            except Exception:
                log.warning(
                    "jax.profiler.stop_trace() failed; the trace under %r "
                    "may be incomplete",
                    log_dir,
                    exc_info=True,
                )


class Counter:
    """Tiny run-length metric accumulator for the serving surface.

    Migrated onto the obs registry (obs/metrics.py): each named Counter
    doubles its (n, total_ms) into `dllama_<name>_events_total` /
    `dllama_<name>_ms_total` so CLI-side token accounting shows up on a
    server's ``GET /metrics`` scrape. The local ``n``/``total_ms``/``rate``
    surface is unchanged (and is what the printers read) — the registry
    copies are the exported view."""

    def __init__(self, name: str = ""):
        self.n = 0
        self.total_ms = 0.0
        self._m_events = self._m_ms = None
        if name:
            from ..obs.metrics import get_registry

            reg = get_registry()
            self._m_events = reg.counter(
                f"dllama_{name}_events_total",
                f"Events accumulated by the {name!r} telemetry counter.",
            )
            self._m_ms = reg.counter(
                f"dllama_{name}_ms_total",
                f"Milliseconds accumulated by the {name!r} telemetry "
                "counter.",
            )

    def add(self, ms: float, n: int = 1) -> None:
        self.n += n
        self.total_ms += ms
        if self._m_events is not None:
            self._m_events.inc(n)
            self._m_ms.inc(ms)

    @property
    def rate(self) -> float:
        return self.n * 1000.0 / self.total_ms if self.total_ms > 0 else 0.0
