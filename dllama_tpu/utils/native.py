"""ctypes bindings for the native (C++) data-loader kernels.

The multithreaded C++ path (native/dllama_native.cpp) unpacks Q40 blocks
straight into the transposed device layout in one pass; the numpy fallback
keeps everything working when the library isn't built (`make -C native`).
Auto-builds on first use when a toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libdllama_native.so"))

_lib = None
_lib_tried = False


def _threads() -> int:
    return max(1, min(os.cpu_count() or 1, 16))


_ABI_VERSION = 3


def _needs_build() -> bool:
    if not os.path.isfile(_LIB_PATH):
        return True
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        nat = os.path.abspath(_NATIVE_DIR)
        return any(
            os.path.getmtime(os.path.join(nat, f)) > lib_mtime
            for f in ("dllama_native.cpp", "Makefile")
        )
    except OSError:
        return False


def _open_library():
    lib = ctypes.CDLL(_LIB_PATH)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    i8 = ctypes.POINTER(ctypes.c_int8)
    f32 = ctypes.POINTER(ctypes.c_float)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i64 = ctypes.c_int64
    lib.q40_unpack_transposed.argtypes = [u8, i64, i64, i8, f32, ctypes.c_int]
    lib.q40_dequant_transposed.argtypes = [u8, i64, i64, f32, ctypes.c_int]
    lib.q40_dequant.argtypes = [u8, i64, i64, f32, ctypes.c_int]
    lib.f32_transpose.argtypes = [f32, i64, i64, f32, ctypes.c_int]
    lib.bpe_index_new.argtypes = [u8, i64p, f32, i64, i64]
    lib.bpe_index_new.restype = ctypes.c_void_p
    lib.bpe_index_free.argtypes = [ctypes.c_void_p]
    lib.bpe_encode.argtypes = [
        ctypes.c_void_p, u8, i64, i64, ctypes.c_int, i32, i64,
    ]
    lib.bpe_encode.restype = i64
    lib.dllama_native_version.restype = ctypes.c_int
    return lib


def load_library(auto_build: bool = True):
    """Load (building if needed) the native library; None when unavailable.
    The staleness check, incremental `make`, AND the dlopen all happen
    under one file lock — a concurrent process must not dlopen a .so that
    another process's make is mid-way through writing."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if auto_build and _needs_build():
            # build + dlopen under one lock so no process opens a .so
            # another's make is mid-way through writing; a current .so
            # takes the lock-free fast path (works on read-only installs)
            try:
                import fcntl

                with open(_LIB_PATH + ".lock", "w") as lock:
                    fcntl.flock(lock, fcntl.LOCK_EX)
                    if _needs_build():
                        subprocess.run(
                            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
                            capture_output=True,
                            timeout=120,
                            check=True,
                        )
            except Exception:
                pass  # no toolchain / read-only tree: use whatever exists
        if not os.path.isfile(_LIB_PATH):
            return None
        lib = _open_library()
        if lib.dllama_native_version() != _ABI_VERSION:
            raise RuntimeError(
                "native library ABI version mismatch; run make -C native clean"
            )
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def q40_unpack_transposed(
    raw: np.ndarray, rows: int, cols: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """Packed Q40 bytes -> (q int8 [cols, rows], d f32 [cols//32, rows]),
    i.e. directly in quant_matmul's device layout. None if no native lib."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    q = np.empty((cols, rows), dtype=np.int8)
    d = np.empty((cols // 32, rows), dtype=np.float32)
    lib.q40_unpack_transposed(
        _u8ptr(raw),
        rows,
        cols,
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        d.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _threads(),
    )
    return q, d


def q40_dequant_transposed(raw: np.ndarray, rows: int, cols: int) -> np.ndarray | None:
    """Packed Q40 bytes ([rows, cols] logical) -> dense f32 [cols, rows]."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    out = np.empty((cols, rows), dtype=np.float32)
    lib.q40_dequant_transposed(
        _u8ptr(raw), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out


def f32_transpose(arr: np.ndarray) -> np.ndarray | None:
    """Tiled multithreaded [rows, cols] -> [cols, rows] transpose."""
    lib = load_library()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    rows, cols = arr.shape
    out = np.empty((cols, rows), dtype=np.float32)
    lib.f32_transpose(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out


class BpeIndex:
    """Owns a native BPE vocab index (hash map built once). Keeps the
    numpy arrays it points into alive for the handle's lifetime."""

    def __init__(
        self,
        vocab_blob: np.ndarray,  # uint8 concat of all vocab pieces
        offsets: np.ndarray,  # int64 [V + 1]
        scores: np.ndarray,  # float32 [V]
        regular_size: int,
    ):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        # keep referenced buffers alive as long as the handle exists
        self._blob = np.ascontiguousarray(vocab_blob)
        self._offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        self._scores = np.ascontiguousarray(scores, dtype=np.float32)
        self._handle = lib.bpe_index_new(
            _u8ptr(self._blob),
            self._offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(self._scores),
            regular_size,
        )
        if not self._handle:
            raise RuntimeError(
                f"native BPE index rejected vocab (regular_size="
                f"{regular_size}, vocab={len(self._scores)})"
            )

    def encode(
        self, text: bytes, bos_id: int, add_specials: bool
    ) -> list[int] | None:
        """Token ids ([bos_id] prepended when >= 0, participating in the
        merge phase like the Python loop's list does), or None for
        un-tokenizable input — the caller's Python fallback raises the
        detailed error."""
        raw = np.frombuffer(text, dtype=np.uint8)
        cap = max(len(text) + 8, 64)
        out = np.empty(cap, dtype=np.int32)
        n = self._lib.bpe_encode(
            self._handle,
            _u8ptr(raw) if len(raw) else _u8ptr(np.zeros(1, np.uint8)),
            len(raw),
            bos_id,
            1 if add_specials else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap,
        )
        if n < 0:
            return None  # -2 untokenizable / -1 capacity
        return out[:n].tolist()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        lib = getattr(self, "_lib", None)
        if handle and lib is not None:
            try:
                lib.bpe_index_free(handle)
            except Exception:
                pass


def make_bpe_index(
    vocab_blob: np.ndarray,
    offsets: np.ndarray,
    scores: np.ndarray,
    regular_size: int,
) -> BpeIndex | None:
    """BpeIndex, or None when the native library is unavailable or the
    vocab metadata is rejected (callers fall back to the Python loop)."""
    if load_library() is None:
        return None
    try:
        return BpeIndex(vocab_blob, offsets, scores, regular_size)
    except RuntimeError:
        return None


def q40_dequant(raw: np.ndarray, rows: int, cols: int) -> np.ndarray | None:
    """Packed Q40 bytes -> dense f32 [rows, cols] (file order)."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    out = np.empty((rows, cols), dtype=np.float32)
    lib.q40_dequant(
        _u8ptr(raw), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out
