"""ctypes bindings for the native (C++) data-loader kernels.

The multithreaded C++ path (native/dllama_native.cpp) unpacks Q40 blocks
straight into the transposed device layout in one pass; the numpy fallback
keeps everything working when the library isn't built (`make -C native`).
Auto-builds on first use when a toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libdllama_native.so"))

_lib = None
_lib_tried = False


def _threads() -> int:
    return max(1, min(os.cpu_count() or 1, 16))


def load_library(auto_build: bool = True):
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if not os.path.isfile(_LIB_PATH) and auto_build:
        try:
            import fcntl

            # serialize concurrent first-use builds (pytest-xdist, multi-
            # process launches): one builder, others wait on the lock
            lock_path = _LIB_PATH + ".lock"
            with open(lock_path, "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if not os.path.isfile(_LIB_PATH):
                    subprocess.run(
                        ["make", "-C", os.path.abspath(_NATIVE_DIR)],
                        capture_output=True,
                        timeout=120,
                        check=True,
                    )
        except Exception:
            return None
    if not os.path.isfile(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        u8 = ctypes.POINTER(ctypes.c_uint8)
        i8 = ctypes.POINTER(ctypes.c_int8)
        f32 = ctypes.POINTER(ctypes.c_float)
        i64 = ctypes.c_int64
        lib.q40_unpack_transposed.argtypes = [u8, i64, i64, i8, f32, ctypes.c_int]
        lib.q40_dequant_transposed.argtypes = [u8, i64, i64, f32, ctypes.c_int]
        lib.q40_dequant.argtypes = [u8, i64, i64, f32, ctypes.c_int]
        lib.f32_transpose.argtypes = [f32, i64, i64, f32, ctypes.c_int]
        lib.dllama_native_version.restype = ctypes.c_int
        if lib.dllama_native_version() != 1:  # not assert: survives python -O
            raise RuntimeError("native library ABI version mismatch; run make clean")
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def q40_unpack_transposed(
    raw: np.ndarray, rows: int, cols: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """Packed Q40 bytes -> (q int8 [cols, rows], d f32 [cols//32, rows]),
    i.e. directly in quant_matmul's device layout. None if no native lib."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    q = np.empty((cols, rows), dtype=np.int8)
    d = np.empty((cols // 32, rows), dtype=np.float32)
    lib.q40_unpack_transposed(
        _u8ptr(raw),
        rows,
        cols,
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        d.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _threads(),
    )
    return q, d


def q40_dequant_transposed(raw: np.ndarray, rows: int, cols: int) -> np.ndarray | None:
    """Packed Q40 bytes ([rows, cols] logical) -> dense f32 [cols, rows]."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    out = np.empty((cols, rows), dtype=np.float32)
    lib.q40_dequant_transposed(
        _u8ptr(raw), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out


def f32_transpose(arr: np.ndarray) -> np.ndarray | None:
    """Tiled multithreaded [rows, cols] -> [cols, rows] transpose."""
    lib = load_library()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    rows, cols = arr.shape
    out = np.empty((cols, rows), dtype=np.float32)
    lib.f32_transpose(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out


def q40_dequant(raw: np.ndarray, rows: int, cols: int) -> np.ndarray | None:
    """Packed Q40 bytes -> dense f32 [rows, cols] (file order)."""
    lib = load_library()
    if lib is None:
        return None
    raw = np.ascontiguousarray(np.frombuffer(raw, dtype=np.uint8))
    out = np.empty((rows, cols), dtype=np.float32)
    lib.q40_dequant(
        _u8ptr(raw), rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _threads(),
    )
    return out
