"""Version shims for the jax API surface this repo spans.

`shard_map` moved from `jax.experimental.shard_map` to the `jax` top level,
and its replication-check kwarg was renamed `check_rep` -> `check_vma` along
the way. Every call site goes through `shard_map_compat` so the rest of the
codebase can use the modern spelling on either jax.
"""

from __future__ import annotations


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    try:
        from jax import shard_map as _shard_map  # jax >= 0.6

        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
        )
