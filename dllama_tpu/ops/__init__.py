from .jnp_ops import (
    rms_norm,
    qk_rms_norm,
    silu,
    gelu,
    rope_frequencies,
    rope_cache,
    apply_rope,
)

__all__ = [
    "rms_norm",
    "qk_rms_norm",
    "silu",
    "gelu",
    "rope_frequencies",
    "rope_cache",
    "apply_rope",
]
