"""Reference jnp implementations of the model ops.

These are the semantic twins of the reference's CPU kernels
(src/nn/nn-cpu-ops.cpp); the Pallas kernels in ops/pallas/* are validated
against them (the same cross-implementation equivalence strategy the
reference uses for SIMD vs scalar and Vulkan vs CPU — SURVEY.md §4).

Everything here is shape-polymorphic jnp, jit-safe, and f32-accumulating:
norms, RoPE and softmax stay in f32 regardless of the activation dtype,
matching the reference numerics (all its kernels accumulate in f32).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..formats.model_file import LlmHeader, RopeType


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMS norm over the last axis (reference: OP_INV_RMS + OP_RMS_NORM,
    src/nn/nn-cpu-ops.cpp:114-189 — the reference splits the inverse-rms
    reduce from the scale so one reduce can feed several columns; under XLA
    that split is fusion, not an op boundary)."""
    xf = x.astype(jnp.float32)
    inv = jnp.reciprocal(jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))
    return (xf * inv * weight.astype(jnp.float32)).astype(x.dtype)


def qk_rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Per-head RMS norm for Qwen3 QK-norm: ``x`` is [..., nHeads, headDim],
    ``weight`` is [headDim] (reference: the nQNormColumns-column variant of
    OP_INV_RMS/OP_RMS_NORM, src/llm.cpp:322-346)."""
    return rms_norm(x, weight, eps)


def silu(x: jnp.ndarray) -> jnp.ndarray:
    """(reference: src/nn/nn-cpu-ops.cpp:454-478)"""
    xf = x.astype(jnp.float32)
    return (xf / (1.0 + jnp.exp(-xf))).astype(x.dtype)


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    """tanh-approx GELU (reference: gelu_F32, src/nn/nn-cpu-ops.cpp:480-500)."""
    xf = x.astype(jnp.float32)
    return (
        0.5
        * xf
        * (1.0 + jnp.tanh(0.797884560802865 * (xf + 0.044715 * xf * xf * xf)))
    ).astype(x.dtype)


def _scale_frequency_llama3(freq: "np.ndarray", h: LlmHeader) -> "np.ndarray":
    """Llama-3.1 NTK-by-parts frequency scaling
    (reference: src/nn/nn-core.cpp:326-340)."""
    wave_len = 2.0 * np.pi / freq
    high_freq_wavelen = h.rope_scaling_orig_max_seq_len / h.rope_scaling_high_freq_factor
    low_freq_wavelen = h.rope_scaling_orig_max_seq_len / h.rope_scaling_low_freq_factor
    smooth = (h.rope_scaling_orig_max_seq_len / wave_len - h.rope_scaling_low_freq_factor) / (
        h.rope_scaling_high_freq_factor - h.rope_scaling_low_freq_factor
    )
    return np.where(
        wave_len < high_freq_wavelen,
        freq,
        np.where(
            wave_len > low_freq_wavelen,
            freq / h.rope_scaling_factor,
            (1.0 - smooth) * freq / h.rope_scaling_factor + smooth * freq,
        ),
    )


def rope_frequencies(h: LlmHeader) -> "np.ndarray":
    """Per-pair inverse frequencies, shape [headDim // 2], f32, on host.

    The reference computes ``theta^{-(i % headDim)/headDim}`` for even i
    (llama layout, src/nn/nn-core.cpp:342-359) and ``theta^{-2j/headDim}``
    for the falcon layout (src/nn/nn-core.cpp:361-374) — identical values,
    different pairing; the pairing lives in `apply_rope`.
    """
    half = h.head_dim // 2
    exponents = 2.0 * np.arange(half, dtype=np.float32) / np.float32(h.head_dim)
    freqs = (1.0 / (h.rope_theta**exponents)).astype(np.float32)
    if h.rope_type == RopeType.LLAMA3_1 and h.rope_scaling_factor != 1.0:
        freqs = _scale_frequency_llama3(freqs, h).astype(np.float32)
    return freqs


def rope_cache(h: LlmHeader, seq_len: int | None = None):
    """(cos, sin) host numpy tables of shape [seqLen, headDim // 2]
    (reference: fullfillRopeCache, src/nn/nn-core.cpp:376-383).

    Computed on host deliberately: the tables are load-time constants placed
    by the loader's `put` hook, so building them on-device would just buy a
    device->host->device round trip."""
    if seq_len is None:
        seq_len = h.seq_len
    freqs = rope_frequencies(h)
    angles = np.arange(seq_len, dtype=np.float32)[:, None] * freqs[None, :]
    return np.cos(angles), np.sin(angles)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    interleaved: bool,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., T, nHeads, headDim] by position.

    ``cos``/``sin`` are [T, headDim//2] rows for the absolute positions of
    the T axis — or [B, T, headDim//2] when lanes sit at different
    positions (per-lane decode). ``interleaved=True`` pairs (2j, 2j+1) —
    the llama layout the converter permutes q/k for (reference:
    ropeLlama_F32, src/nn/nn-cpu-ops.cpp:843-863); ``False`` pairs
    (j, j+headDim/2) — the falcon/neox layout used by Qwen3
    (src/nn/nn-cpu-ops.cpp:865-885).
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    c = cos[..., :, None, :]  # [(B,) T, 1, half]
    s = sin[..., :, None, :]
    if interleaved:
        x0 = xf[..., 0::2]
        x1 = xf[..., 1::2]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.stack([r0, r1], axis=-1).reshape(xf.shape)
    else:
        half = xf.shape[-1] // 2
        x0 = xf[..., :half]
        x1 = xf[..., half:]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.concatenate([r0, r1], axis=-1)
    return out.astype(dtype)


_NEG_INF = -1e30


def attention_stats(
    q: jnp.ndarray,  # [B, Tq, H, hd]
    k: jnp.ndarray,  # [B, KH, Ts, hd] — head-major cache layout
    v: jnp.ndarray,  # [B, KH, Ts, hd]
    q_pos0,  # scalar or [B]: absolute position of q[:, 0] (per lane)
    s_pos0,  # scalar: absolute position of k[:, :, 0]
    s_stride: int = 1,  # position step between consecutive key rows
):
    """Causal GQA attention partial state (unnormalized acc, running max m,
    denominator l) in f32 — the single source of the reference's
    multiheadAtt_F32 math (src/nn/nn-cpu-ops.cpp:753-788). Dense attention
    normalizes it directly; ring attention merges several of these across
    sequence shards. A vector ``q_pos0`` gives each batch lane its own
    position (independent decode lanes).

    The cache is HEAD-MAJOR ([B, KH, S, hd]): per-KV-head tiles are then
    (seq, head_dim) planes whose Pallas BlockSpecs satisfy Mosaic's
    last-two-dims tiling rule for any head_dim — blocking a size-1 head
    inside the last two dims of a [B, S, KH, hd] array is rejected by the
    real TPU compiler (and pads (KH, hd) tiles up to (8, 128))."""
    b, tq, h, hd = q.shape
    kh, ts = k.shape[1], k.shape[2]
    g = h // kh
    qf = q.astype(jnp.float32).reshape(b, tq, kh, g, hd)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scores = jnp.einsum("btkgh,bksh->bkgts", qf, kf) / jnp.sqrt(jnp.float32(hd))
    q_pos0_arr = jnp.atleast_1d(jnp.asarray(q_pos0, jnp.int32))  # [1] or [B]
    q_pos = q_pos0_arr[:, None] + jnp.arange(tq, dtype=jnp.int32)[None, :]
    # s_stride > 1: CYCLIC sequence layout — local key row j holds the
    # global position s_pos0 + j*stride (sp shard of a strided cache;
    # see parallel/sharding.cache_specs / docs on sp windows)
    s_pos = s_pos0 + jnp.arange(ts, dtype=jnp.int32) * s_stride
    mask = s_pos[None, None, :] <= q_pos[:, :, None]  # [1 or B, tq, ts]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b, kh, g, tq]
    p = jnp.exp(scores - m[..., None])
    # fully-masked rows (query before every key in this shard) -> zero
    p = jnp.where(m[..., None] <= _NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkgts,bksh->bkgth", p, vf)
    return acc, m, l


def attention_dense(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,
    pos,  # scalar: absolute position of q[:, 0]
) -> jnp.ndarray:
    """Normalized causal GQA attention over the cache; [B, T, H, hd]."""
    b, t, h, hd = q.shape
    acc, m, l = attention_stats(q, k_cache, v_cache, pos, 0)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]  # [b, kh, g, tq, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, h, hd).astype(q.dtype)
