"""Causal GQA flash attention over the positional KV cache (Pallas TPU).

Replaces the reference's multiheadAtt_F32 (src/nn/nn-cpu-ops.cpp:753-788)
for prefill: the reference materializes a per-head [seqLen] score row per
query (O(T*S) memory); blockwise online-softmax keeps everything in VMEM
tiles, which is what makes 100k+ context feasible (SURVEY.md §5 calls this
out as the biggest idiomatic upgrade over the reference).

Semantics match models/transformer._attention exactly:
  * queries at absolute positions pos..pos+T-1 attend to cache rows
    0..q_pos (causal, inclusive);
  * GQA: q head h reads kv head h // (H // KH);
  * f32 softmax/accumulation, bf16/f32 inputs.

Kernel layout: grid (B * H, T blocks, S blocks), S innermost so the online
softmax state (m, l, acc) lives in VMEM scratch across S steps. S blocks
entirely above the causal diagonal are compute-skipped via pl.when.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def pick_flash_blocks(t: int, s: int) -> tuple[int, int] | None:
    """(block_t, block_s) that divide the shapes, or None when the flash
    kernel can't run them (callers then fall back to dense attention).
    block_t: largest multiple of 8 <= 256 dividing t; block_s: largest
    multiple of 128 <= 512 dividing s."""
    bt = next((b for b in range(min(256, t), 0, -8) if t % b == 0), None)
    bs = next((b for b in range(min(512, s - s % 128), 0, -128) if s % b == 0), None)
    if not bt or not bs:
        return None
    return bt, bs


def attention_ref(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, S, KH, hd]
    v_cache: jnp.ndarray,  # [B, S, KH, hd]
    pos: jnp.ndarray,  # scalar int32
) -> jnp.ndarray:
    """jnp reference: the canonical masked-softmax math from ops/jnp_ops
    (same source the model's dense path and ring attention use)."""
    from .jnp_ops import attention_dense

    return attention_dense(q, k_cache, v_cache, pos)


def _flash_kernel(
    pos_ref,  # SMEM scalar prefetch: [1] int32 absolute start position
    q_ref,  # [1, bt, hd]
    k_ref,  # [1, bs, hd]
    v_ref,  # [1, bs, hd]
    o_ref,  # [1, bt, hd]
    m_ref,  # VMEM [bt, 128] running max
    l_ref,  # VMEM [bt, 128] running denominator
    acc_ref,  # VMEM [bt, hd] weighted-value accumulator
    *,
    block_t: int,
    block_s: int,
    n_s: int,
    scale: float,
):
    ti = pl.program_id(1)
    si = pl.program_id(2)
    pos = pos_ref[0]

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # absolute positions of this tile's queries and keys
    q_pos0 = pos + ti * block_t  # first query's absolute position
    s_start = si * block_s

    # the whole S block is above the causal diagonal for every query in the
    # T block -> skip (the highest query position is q_pos0 + block_t - 1)
    @pl.when(s_start <= q_pos0 + block_t - 1)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # [bt, bs]
        q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (block_t, block_s), 0)
        s_pos = s_start + jax.lax.broadcasted_iota(jnp.int32, (block_t, block_s), 1)
        scores = jnp.where(s_pos <= q_pos, scores, _NEG_INF)

        m_prev = m_ref[:, :1]  # [bt, 1]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # rescale of previous state
        p = jnp.exp(scores - m_new)  # [bt, bs]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(si == n_s - 1)
    def _emit():
        # l is 0 only if every key was masked, which cannot happen for a
        # causal query at position >= 0 (it always sees itself)
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_t", "block_s", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, S, KH, hd]
    v_cache: jnp.ndarray,  # [B, S, KH, hd]
    pos: jnp.ndarray,  # scalar int32
    block_t: int = 0,
    block_s: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Blockwise causal GQA attention; returns [B, T, H, hd] in q.dtype.

    Default block sizes come from `pick_flash_blocks`, which guarantees
    divisibility; explicit blocks must divide t/s."""
    b, t, h, hd = q.shape
    s, kh = k_cache.shape[1], k_cache.shape[2]
    g = h // kh
    if not block_t or not block_s:
        picked = pick_flash_blocks(t, s)
        if picked is None:
            raise ValueError(
                f"no valid flash blocks for t={t}, s={s}; use dense attention"
            )
        auto_t, auto_s = picked
        block_t = block_t or auto_t
        block_s = block_s or auto_s
    assert t % block_t == 0, (t, block_t)
    assert s % block_s == 0, (s, block_s)
    n_t = t // block_t
    n_s = s // block_s
    scale = 1.0 / (hd**0.5)

    # [B, T, H, hd] -> [B*H, T, hd]; kv gets a broadcast-free gather of the
    # right kv head per q head via the index map (no repeat materialized)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * kh, s, hd)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * kh, s, hd)

    pos_arr = jnp.asarray([pos], dtype=jnp.int32).reshape(1)

    grid = (b * h, n_t, n_s)

    # with num_scalar_prefetch=1 the index maps receive the prefetch ref
    # as a trailing argument
    def q_map(bh, ti, si, pos_ref):
        return (bh, ti, 0)

    def kv_map(bh, ti, si, pos_ref):
        # q row bh = bi * h + hi -> kv row bi * kh + hi // g
        bi = bh // h
        hi = bh % h
        return (bi * kh + hi // g, si, 0)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_t=block_t,
            block_s=block_s,
            n_s=n_s,
            scale=scale,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_t, hd), q_map),
                pl.BlockSpec((1, block_s, hd), kv_map),
                pl.BlockSpec((1, block_s, hd), kv_map),
            ],
            out_specs=pl.BlockSpec((1, block_t, hd), q_map),
            scratch_shapes=[
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, t, hd), q.dtype),
        interpret=interpret,
    )(pos_arr, qt, kt, vt)
    return out.reshape(b, h, t, hd).transpose(0, 2, 1, 3)
