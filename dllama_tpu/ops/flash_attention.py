"""Causal GQA flash attention over the positional KV cache (Pallas TPU).

Replaces the reference's multiheadAtt_F32 (src/nn/nn-cpu-ops.cpp:753-788)
for prefill: the reference materializes a per-head [seqLen] score row per
query (O(T*S) memory); blockwise online-softmax keeps everything in VMEM
tiles, which is what makes 100k+ context feasible (SURVEY.md §5 calls this
out as the biggest idiomatic upgrade over the reference).

Semantics match models/transformer._attention exactly:
  * queries at absolute positions pos..pos+T-1 attend to cache rows
    0..q_pos (causal, inclusive);
  * GQA: q head h reads kv head h // (H // KH);
  * f32 softmax/accumulation, bf16/f32 inputs.

Kernel layout: grid (B * H, T blocks, S blocks), S innermost so the online
softmax state (m, l, acc) lives in VMEM scratch across S steps. S blocks
entirely above the causal frontier are compute-skipped via pl.when, and
their kv index map is clamped to the causal frontier. NOTE (round-3
silicon finding, scripts/decode_probe.py): Mosaic does NOT elide the
HBM->VMEM copy when a block index repeats, so the clamp bounds COMPUTE
but not DMA traffic — per-call cache reads are O(S), which is why the
engine bounds decode reads with bucketed attn_window slicing instead and
uses these kernels only where blockwise softmax itself is the win
(prefill's [T, S] score materialization). The cache is HEAD-MAJOR
[B, KH, S, hd]: each grid step's kv tile is a (block_s, hd) plane of one
head, which satisfies Mosaic's last-two-dims tiling rule for any head_dim
(a [B, S, KH, hd] layout would need an illegal size-1 head block inside
the last two dims — rejected on real silicon) and avoids
(KH, hd) -> (8, 128) tile padding in HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kv_cache import QuantKV

_NEG_INF = -1e30


def pick_flash_blocks(t: int, s: int) -> tuple[int, int] | None:
    """(block_t, block_s) that divide the shapes, or None when the flash
    kernel can't run them (callers then fall back to dense attention).
    block_t: largest multiple of 8 <= 256 dividing t; block_s: largest
    multiple of 128 <= 512 dividing s."""
    bt = next((b for b in range(min(256, t), 0, -8) if t % b == 0), None)
    bs = next((b for b in range(min(512, s - s % 128), 0, -128) if s % b == 0), None)
    if not bt or not bs:
        return None
    return bt, bs


def attention_ref(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,  # [B, KH, S, hd]
    pos: jnp.ndarray,  # scalar int32
) -> jnp.ndarray:
    """jnp reference: the canonical masked-softmax math from ops/jnp_ops
    (same source the model's dense path and ring attention use)."""
    from .jnp_ops import attention_dense

    return attention_dense(q, k_cache, v_cache, pos)


def _flash_stats_kernel(
    pos_ref,  # SMEM scalar prefetch: [B] int32 per-lane q start positions
    spos_ref,  # SMEM scalar prefetch: [1] int32 (s_pos0)
    q_ref,  # [1, bt, hd]
    k_ref,  # [1, 1, bs, hd] — one head's (seq, hd) plane
    v_ref,  # [1, 1, bs, hd]
    *rest,  # quant_kv: (ks_ref [1,1,bs,1], vs_ref [1,1,bs,1]); then
    #         outputs (acc_out [1,bt,hd], m_out [1,bt,128], l_out
    #         [1,bt,128]) and scratch (m_ref, l_ref, acc_ref)
    block_t: int,
    block_s: int,
    n_s: int,
    n_heads: int,
    scale: float,
    s_stride: int = 1,
    quant_kv: bool = False,
):
    """Like _flash_kernel but emits UNNORMALIZED online-softmax partial
    state (acc, m, l) — the drop-in local step for ring attention's
    log-sum-exp merge (parallel/ring_attention.py). Query positions are
    per LANE (pos_ref[b]); a lane position <= -T keeps EVERY query row of
    the chunk negative (the engine's parked lanes use -(cache length)),
    producing fully-masked stats at one block of DMA. A bare -1 would
    only mask the first row of a multi-row chunk. `s_stride` > 1: the
    key rows are a CYCLIC sequence shard (row j at global position
    s_pos0 + j*stride — the windowable sp layout, see
    models/transformer._attention_sp_merge); positions and the causal
    frontier scale by the stride. `quant_kv`: k/v tiles arrive int8 with
    per-row f32 scales as two extra [bs, 1]-blocked refs sharing the kv
    index map — dequant happens HERE on the VMEM tile, so HBM traffic is
    the int8 bytes (VERDICT r4 #3), amortized over the tile's bt queries."""
    if quant_kv:
        ks_ref, vs_ref, acc_out, m_out, l_out, m_ref, l_ref, acc_ref = rest
    else:
        acc_out, m_out, l_out, m_ref, l_ref, acc_ref = rest
    ti = pl.program_id(1)
    si = pl.program_id(2)
    q_pos0 = pos_ref[pl.program_id(0) // n_heads] + ti * block_t
    s_pos0 = spos_ref[0]

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s_start = s_pos0 + si * block_s * s_stride

    @pl.when(s_start <= q_pos0 + block_t - 1)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        if quant_kv:
            k = k * ks_ref[0, 0]  # (bs, 1) per-row scales, lane-broadcast
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )
        q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (block_t, block_s), 0)
        s_pos = s_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_t, block_s), 1
        ) * s_stride
        scores = jnp.where(s_pos <= q_pos, scores, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        # fully-masked tiles keep exp(-inf - -inf) out of the stats
        p = jnp.where(m_new <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_prev <= _NEG_INF / 2, 0.0, alpha)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        if quant_kv:
            v = v * vs_ref[0, 0]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(si == n_s - 1)
    def _emit():
        acc_out[0] = acc_ref[:]
        m_out[0] = m_ref[:]
        l_out[0] = l_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=("block_t", "block_s", "interpret", "s_stride"),
)
def flash_attention_stats(
    q: jnp.ndarray,  # [B, T, H, hd]
    k: jnp.ndarray,  # [B, KH, S, hd]
    v: jnp.ndarray,  # [B, KH, S, hd]
    q_pos0: jnp.ndarray,  # scalar or [B] int32: position of q[:, 0] per lane
    s_pos0: jnp.ndarray,  # scalar int32: absolute position of k[:, 0]
    block_t: int = 0,
    block_s: int = 0,
    interpret: bool = False,
    s_stride: int = 1,
):
    """Blockwise causal GQA attention partial state: returns f32
    (acc [B, KH, G, T, hd], m [B, KH, G, T], l [B, KH, G, T]) — the same
    contract as ops/jnp_ops.attention_stats, MXU-tiled. A vector q_pos0
    gives each lane its own query start (per-lane prefill); a strongly
    negative lane position masks that lane entirely at one block of DMA.
    `s_stride` > 1 treats the key rows as a cyclic sequence shard (row j
    at global position s_pos0 + j*stride) — the sp layout whose windows
    tile shards; masks and the causal-frontier DMA clamp scale by it.

    `k`/`v` may be QuantKV (int8 values + f32 [.., S, 1] per-row scales):
    the kernel then DMAs the int8 planes plus a [bs, 1]-blocked scale ref
    and dequants on the VMEM tile — int8 prefill reads ~half the HBM
    bytes of bf16 and never materializes a dense cache copy (the pre-r5
    behavior; VERDICT r4 #3)."""
    quant_kv = isinstance(k, QuantKV)
    if isinstance(v, QuantKV) != quant_kv:
        raise TypeError(
            f"k and v must both be QuantKV or both dense, got "
            f"k={type(k).__name__}, v={type(v).__name__}"
        )
    b, t, h, hd = q.shape
    kh, s = k.shape[1], k.shape[2]
    g = h // kh
    if not block_t or not block_s:
        picked = pick_flash_blocks(t, s)
        if picked is None:
            if not interpret:
                # same contract as flash_attention: Mosaic needs aligned
                # tiles; callers fall back to the dense path
                raise ValueError(
                    f"no valid flash blocks for t={t}, s={s}; use dense attention"
                )
            picked = (t, s)  # interpret-mode tests: single tile is fine
        auto_t, auto_s = picked
        block_t = block_t or auto_t
        block_s = block_s or auto_s
    assert t % block_t == 0 and s % block_s == 0, (t, s, block_t, block_s)
    n_t = t // block_t
    n_s = s // block_s
    scale = 1.0 / (hd**0.5)

    # queries transpose is chunk-sized (cheap); the cache is consumed in
    # its storage layout [B, KH, S, hd] — no copy of the S rows is ever
    # materialized
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    pos_arr = jnp.broadcast_to(
        jnp.atleast_1d(jnp.asarray(q_pos0, jnp.int32)), (b,)
    )
    spos_arr = jnp.asarray(s_pos0, jnp.int32).reshape(1)

    def q_map(bh, ti, si, pos_ref, spos_ref):
        return (bh, ti, 0)

    def kv_map(bh, ti, si, pos_ref, spos_ref):
        # clamp past the causal frontier of this query tile (fully-masked
        # tiles re-fetch the frontier block: compute is skipped but Mosaic
        # does not elide the repeated-index DMA — see module docstring);
        # strided shards divide the frontier by the stride first
        limit = jnp.maximum(
            (pos_ref[bh // h] + (ti + 1) * block_t - 1 - spos_ref[0])
            // s_stride
            // block_s,
            0,
        )
        return (bh // h, (bh % h) // g, jnp.minimum(si, limit), 0)

    in_specs = [
        pl.BlockSpec((1, block_t, hd), q_map),
        pl.BlockSpec((1, 1, block_s, hd), kv_map),
        pl.BlockSpec((1, 1, block_s, hd), kv_map),
    ]
    operands = [qt, k, v]
    if quant_kv:
        # scale refs ride the SAME index map as their value planes; the
        # trailing dim is array-size 1 fully covered by the block (unlike
        # the r3 blocker — a size-1 BLOCK of a larger dim in the last two
        # dims — this tiles a genuine [.., S, 1] tensor)
        in_specs = [
            in_specs[0],
            in_specs[1],
            in_specs[2],
            pl.BlockSpec((1, 1, block_s, 1), kv_map),
            pl.BlockSpec((1, 1, block_s, 1), kv_map),
        ]
        operands = [qt, k.q, v.q, k.s, v.s]
    acc, m, l = pl.pallas_call(
        functools.partial(
            _flash_stats_kernel,
            block_t=block_t,
            block_s=block_s,
            n_s=n_s,
            n_heads=h,
            scale=scale,
            s_stride=s_stride,
            quant_kv=quant_kv,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * h, n_t, n_s),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_t, hd), q_map),
                pl.BlockSpec((1, block_t, 128), q_map),
                pl.BlockSpec((1, block_t, 128), q_map),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * h, t, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * h, t, 128), jnp.float32),
        ],
        interpret=interpret,
    )(pos_arr, spos_arr, *operands)

    # [B*H, T, ...] -> [B, KH, G, T, ...]
    acc = acc.reshape(b, kh, g, t, hd)
    m = m[:, :, 0].reshape(b, kh, g, t)
    l = l[:, :, 0].reshape(b, kh, g, t)
    return acc, m, l


def _flash_decode_kernel(
    pos_ref,  # SMEM scalar prefetch: [B] int32 (per-lane query positions)
    spos_ref,  # SMEM scalar prefetch: [1] int32 (this KV shard's first pos)
    q_ref,  # [1, G, hd] (the G query heads sharing this KV head)
    k_ref,  # [1, 1, bs, hd] — one head's (seq, hd) plane
    v_ref,  # [1, 1, bs, hd]
    *rest,  # emit_stats: (acc_out [1,G,hd], m_out [1,G,128], l_out [1,G,128])
    #         else: (o_ref [1,G,hd]); then scratch (m_ref, l_ref, acc_ref)
    block_s: int,
    n_s: int,
    n_kv_heads: int,
    scale: float,
    emit_stats: bool,
):
    """T=1 decode step: one query token per lane group, online softmax
    over S blocks. Blocks entirely beyond `pos` are compute-skipped and
    their kv index clamps to pos's block — but on real Mosaic the
    repeated-index DMA is NOT elided (scripts/decode_probe.py), so cache
    reads stay O(S) per call and the ENGINE does not use this kernel for
    decode anymore (windowed XLA dense attention measured faster there);
    it is kept as the op-level T=1 flash surface and for stats emission.
    Positions are per LANE (pos_ref[b]). With `emit_stats` the kernel
    emits the UNNORMALIZED (acc, m, l) partial state relative to a KV
    shard starting at absolute position spos_ref[0] (the contract
    models/transformer._attention_sp's merge consumes)."""
    if emit_stats:
        acc_out, m_out, l_out, m_ref, l_ref, acc_ref = rest
    else:
        (o_ref, m_ref, l_ref, acc_ref) = rest
    si = pl.program_id(1)
    pos = pos_ref[pl.program_id(0) // n_kv_heads]
    # highest LOCAL row index this query may see (negative: whole shard
    # is in the future -> nothing computes, stats emit as fully-masked)
    local_limit = pos - spos_ref[0]

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s_start = si * block_s

    @pl.when(s_start <= local_limit)
    def _compute():
        g = q_ref.shape[1]
        q = q_ref[0].astype(jnp.float32)  # [G, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, hd]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [G, bs]
        s_row = s_start + jax.lax.broadcasted_iota(
            jnp.int32, (g, block_s), 1
        )
        scores = jnp.where(s_row <= local_limit, scores, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        p = jnp.where(m_new <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_prev <= _NEG_INF / 2, 0.0, alpha)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(si == n_s - 1)
    def _emit():
        if emit_stats:
            acc_out[0] = acc_ref[:]
            m_out[0] = m_ref[:]
            l_out[0] = l_ref[:]
        else:
            # pos indexes a row written this step (the engine appends k/v
            # at pos before attention), so l >= 1 always; the guard is
            # belt and braces for direct op-level callers
            l_safe = jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
            o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def pick_decode_block(s: int) -> int | None:
    """KV block length for the decode kernel: largest multiple of 128
    <= 1024 dividing s, or None (caller falls back to dense)."""
    return next(
        (b for b in range(min(1024, s - s % 128), 0, -128) if s % b == 0),
        None,
    )


@functools.partial(
    jax.jit, static_argnames=("block_s", "interpret", "emit_stats")
)
def _flash_decode_impl(
    q: jnp.ndarray,  # [B, 1, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,  # [B, KH, S, hd]
    pos: jnp.ndarray,  # scalar int32, or [B] per-lane positions
    s_pos0: jnp.ndarray,  # scalar int32: absolute position of cache row 0
    block_s: int = 0,
    interpret: bool = False,
    emit_stats: bool = False,
):
    """Single-token causal GQA attention over a (possibly shard-local) KV
    range. Normalized output [B, 1, H, hd] (emit_stats=False) or the
    unnormalized (acc, m, l) partial state in attention_stats layout
    (emit_stats=True, the sp decode local step).

    The G = H/KH query heads of each KV group ride the sublane dim (one
    [G, hd] x [hd, block_s] matmul per KV block), and the kv BlockSpec
    index map clamps at pos's block (compute skip only — the repeated
    -index DMA is not elided on Mosaic, so reads are O(S) per call; see
    module docstring). The cache is consumed in its storage layout
    [B, KH, S, hd] via 4-D BlockSpecs — no per-step copy/transpose of the
    cache is ever materialized, and each tile is a Mosaic-legal
    (block_s, hd) plane.
    """
    b, t, h, hd = q.shape
    assert t == 1, "flash_decode is the T=1 path"
    kh, s = k_cache.shape[1], k_cache.shape[2]
    g = h // kh
    if not block_s:
        picked = pick_decode_block(s)
        if picked is None:
            if not interpret:
                raise ValueError(
                    f"no valid decode block for s={s}; use dense attention"
                )
            picked = s
        block_s = picked
    assert s % block_s == 0, (s, block_s)
    n_s = s // block_s
    scale = 1.0 / (hd**0.5)

    # [B, 1, H, hd] -> [B * KH, G, hd] (pure reshape: T=1, no data movement)
    qt = q.reshape(b, kh, g, hd).reshape(b * kh, g, hd)
    pos_arr = jnp.broadcast_to(
        jnp.atleast_1d(jnp.asarray(pos, jnp.int32)), (b,)
    )
    spos_arr = jnp.asarray(s_pos0, jnp.int32).reshape(1)

    def q_map(bk, si, pos_ref, spos_ref):
        return (bk, 0, 0)

    def kv_map(bk, si, pos_ref, spos_ref):
        # clamp to pos's block (fully-masked steps re-fetch that block;
        # compute is skipped but the DMA is not elided — see module note)
        limit = jnp.maximum(pos_ref[bk // kh] - spos_ref[0], 0)
        return (bk // kh, bk % kh, jnp.minimum(si, limit // block_s), 0)

    kernel = functools.partial(
        _flash_decode_kernel,
        block_s=block_s,
        n_s=n_s,
        n_kv_heads=kh,
        scale=scale,
        emit_stats=emit_stats,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * kh, n_s),
        in_specs=[
            pl.BlockSpec((1, g, hd), q_map),
            pl.BlockSpec((1, 1, block_s, hd), kv_map),
            pl.BlockSpec((1, 1, block_s, hd), kv_map),
        ],
        out_specs=(
            [
                pl.BlockSpec((1, g, hd), q_map),
                pl.BlockSpec((1, g, 128), q_map),
                pl.BlockSpec((1, g, 128), q_map),
            ]
            if emit_stats
            else pl.BlockSpec((1, g, hd), q_map)
        ),
        scratch_shapes=[
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, hd), jnp.float32),
        ],
    )
    out_shape = (
        [
            jax.ShapeDtypeStruct((b * kh, g, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kh, g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * kh, g, 128), jnp.float32),
        ]
        if emit_stats
        else jax.ShapeDtypeStruct((b * kh, g, hd), jnp.float32)
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(pos_arr, spos_arr, qt, k_cache, v_cache)

    if emit_stats:
        acc, m, l = out
        # match ops/jnp_ops.attention_stats: acc [B, KH, G, T=1, hd],
        # m/l [B, KH, G, 1]
        acc = acc.reshape(b, kh, g, 1, hd)
        m = m[:, :, 0].reshape(b, kh, g, 1)
        l = l[:, :, 0].reshape(b, kh, g, 1)
        return acc, m, l
    return out.reshape(b, kh, g, hd).reshape(b, 1, h, hd).astype(q.dtype)


def flash_decode(
    q: jnp.ndarray,  # [B, 1, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,
    pos: jnp.ndarray,  # scalar int32, or [B] per-lane positions
    block_s: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Normalized single-token decode attention (see _flash_decode_impl)."""
    return _flash_decode_impl(
        q, k_cache, v_cache, pos, jnp.int32(0),
        block_s=block_s, interpret=interpret, emit_stats=False,
    )


def flash_decode_stats(
    q: jnp.ndarray,  # [B, 1, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, Ss, hd] — one sequence SHARD
    v_cache: jnp.ndarray,
    pos: jnp.ndarray,  # scalar or [B]
    s_pos0: jnp.ndarray,  # absolute position of this shard's row 0
    block_s: int = 0,
    interpret: bool = False,
):
    """Unnormalized (acc, m, l) decode partial state over a KV shard in
    the attention_stats contract (log-sum-exp mergeable). Shards entirely
    in the query's future emit fully-masked stats (m = -inf, l = 0) with
    all compute skipped. No longer the engine's sp local step (the dense
    jnp stats won on silicon; see _attention_sp) — kept as the op-level
    stats surface and covered by tests/test_flash_and_ring.py."""
    return _flash_decode_impl(
        q, k_cache, v_cache, pos, jnp.asarray(s_pos0, jnp.int32),
        block_s=block_s, interpret=interpret, emit_stats=True,
    )


def _paged_decode_kernel(
    pos_ref,  # SMEM scalar prefetch: [B] int32 per-lane query positions
    pt_ref,  # SMEM scalar prefetch: [B, n_blocks] int32 page table
    q_ref,  # [1, G, hd]
    k_ref,  # [1, 1, ps, hd] — one PAGE of one head, via page-table lookup
    v_ref,  # [1, 1, ps, hd]
    *rest,  # quant_kv: (ks_ref [1,1,ps,1], vs_ref [1,1,ps,1]); then
    #         o_ref [1, G, hd] and scratch (m_ref, l_ref, acc_ref)
    page_size: int,
    n_blocks: int,
    n_kv_heads: int,
    scale: float,
    quant_kv: bool = False,
):
    """T=1 decode over a PAGED pool: identical online-softmax body to
    _flash_decode_kernel, but the kv tiles arrive through the page table
    (the index map below) instead of a contiguous per-lane slab, so logical
    block ``si`` of lane ``b`` reads physical page ``pt_ref[b, si]``. A lane
    whose prefix is shared never holds its own copy of those rows."""
    if quant_kv:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    si = pl.program_id(1)
    pos = pos_ref[pl.program_id(0) // n_kv_heads]

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s_start = si * page_size

    @pl.when(s_start <= pos)
    def _compute():
        g = q_ref.shape[1]
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        if quant_kv:
            k = k * ks_ref[0, 0]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        s_row = s_start + jax.lax.broadcasted_iota(jnp.int32, (g, page_size), 1)
        scores = jnp.where(s_row <= pos, scores, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        p = jnp.where(m_new <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_prev <= _NEG_INF / 2, 0.0, alpha)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        if quant_kv:
            v = v * vs_ref[0, 0]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(si == n_blocks - 1)
    def _emit():
        l_safe = jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_flash_decode(
    q: jnp.ndarray,  # [B, 1, H, hd]
    k_pages,  # [P, KH, ps, hd] pool leaf (or QuantKV pair)
    v_pages,
    page_table: jnp.ndarray,  # [B, n_blocks] int32 physical page per logical block
    pos: jnp.ndarray,  # scalar int32, or [B] per-lane positions
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-token causal GQA attention reading KV through a page table.

    This is the page-indirection seam over the flash decode kernel: the kv
    BlockSpec index map resolves logical block ``si`` of lane ``b`` to
    physical pool page ``page_table[b, si]`` (clamped at the lane's causal
    frontier, padding entries point at the reserved scratch page), so lanes
    sharing a prefix read the SAME physical pages — storage is per unique
    prefix, not per lane. Accepts a QuantKV pool (int8 values + per-row
    scales ride the same index map; dequant on the VMEM tile).

    Block length equals the pool's page size. On real Mosaic the same
    caveats as _flash_decode_kernel apply (repeated-index DMAs are not
    elided, and tiny pages under-utilize the (8, 128) tile), so the engine
    keeps windowed dense attention on the decode hot path; this kernel is
    the op-level paged surface, exercised interpret-mode in tests and ready
    for silicon page-size tuning (page_size a multiple of 8 f32 / 16 bf16,
    head_dim a multiple of 128)."""
    quant_kv = isinstance(k_pages, QuantKV)
    if isinstance(v_pages, QuantKV) != quant_kv:
        raise TypeError(
            f"k_pages and v_pages must both be QuantKV or both dense, got "
            f"k={type(k_pages).__name__}, v={type(v_pages).__name__}"
        )
    b, t, h, hd = q.shape
    assert t == 1, "paged_flash_decode is the T=1 path"
    kh, ps = k_pages.shape[1], k_pages.shape[2]
    g = h // kh
    n_blocks = page_table.shape[1]
    scale = 1.0 / (hd**0.5)

    qt = q.reshape(b, kh, g, hd).reshape(b * kh, g, hd)
    pos_arr = jnp.broadcast_to(jnp.atleast_1d(jnp.asarray(pos, jnp.int32)), (b,))
    pt = page_table.astype(jnp.int32)

    def q_map(bk, si, pos_ref, pt_ref):
        return (bk, 0, 0)

    def kv_map(bk, si, pos_ref, pt_ref):
        # page-table indirection with the usual causal-frontier clamp:
        # blocks past the lane's position re-fetch the frontier page
        # (compute skipped); clamping also keeps padding page-table slots
        # (scratch page 0) from ever being DMA'd beyond the frontier
        lane = bk // kh
        limit = jnp.maximum(pos_ref[lane], 0) // ps
        return (pt_ref[lane, jnp.minimum(si, limit)], bk % kh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, g, hd), q_map),
        pl.BlockSpec((1, 1, ps, hd), kv_map),
        pl.BlockSpec((1, 1, ps, hd), kv_map),
    ]
    operands = [qt, k_pages, v_pages]
    if quant_kv:
        in_specs += [
            pl.BlockSpec((1, 1, ps, 1), kv_map),
            pl.BlockSpec((1, 1, ps, 1), kv_map),
        ]
        operands = [qt, k_pages.q, v_pages.q, k_pages.s, v_pages.s]
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel,
            page_size=ps,
            n_blocks=n_blocks,
            n_kv_heads=kh,
            scale=scale,
            quant_kv=quant_kv,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * kh, n_blocks),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, g, hd), q_map),
            scratch_shapes=[
                pltpu.VMEM((g, 128), jnp.float32),
                pltpu.VMEM((g, 128), jnp.float32),
                pltpu.VMEM((g, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * kh, g, hd), jnp.float32),
        interpret=interpret,
    )(pos_arr, pt, *operands)
    return out.reshape(b, kh, g, hd).reshape(b, 1, h, hd).astype(q.dtype)


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,  # [B, KH, S, hd]
    pos: jnp.ndarray,  # scalar int32, or [B] per-lane positions
    block_t: int = 0,
    block_s: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Blockwise causal GQA attention; returns [B, T, H, hd] in q.dtype.

    Implemented as normalize(flash_attention_stats(...)) so one kernel body
    serves both the dense path and ring attention's partial-state merge; the
    extra m/l emission is noise next to the score/value traffic.
    """
    b, t, h, hd = q.shape
    acc, m, l = flash_attention_stats(
        q, k_cache, v_cache, pos, 0,
        block_t=block_t, block_s=block_s, interpret=interpret,
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]  # [B, KH, G, T, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, h, hd).astype(q.dtype)
