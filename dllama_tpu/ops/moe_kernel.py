"""Ragged MoE dispatch: Pallas kernels computing ONLY the active experts.

The decode-path answer to SURVEY.md §7's "MoE top-k on TPU with tiny active
expert counts (A3B: 8 of 128) without wasting a dense 128-expert matmul".
The reference walks an indexes buffer and runs just the selected experts'
matmuls (src/nn/nn-cpu-ops.cpp:1104-1136); the straightforward XLA
restatement (`jnp.take` of the expert weights) measures ~3x slower than
even the dense all-expert einsum on v5e, because the gather materializes
the selected weights through HBM.

These kernels instead make the expert id part of the DMA schedule: the
top-k indices arrive via scalar prefetch and the BlockSpec index_map picks
which expert's weight tile to copy HBM->VMEM per grid step — the selected
expert weights are read exactly once per (token, choice), nothing else
moves.

Grid: (m, k, F blocks) — token-major, active experts next, the expert's
hidden (F) dim innermost. F-blocking is exact (SwiGLU is elementwise in F
and w2 contracts over it) and is what keeps full-scale experts (e.g. A3B:
D=2048, F=768 -> 9 MB of bf16 tiles per step unblocked) inside the 16 MB
scoped-VMEM budget with double buffering — the unblocked version was
rejected by the real compiler at exactly that shape. Routing is PER TOKEN
(each decode lane picks its own top-k, matching the reference's per-row
indexes buffer). Decode-sized m (the engine's dp lanes); prefill keeps the
dense path where every expert is busy anyway.

Two variants:
- `moe_active_experts`: dense bf16/f32 expert weights.
- `moe_active_experts_q40`: block-quantized experts (int8 values +
  per-32-block f32 scales, the `QuantWeight` device layout) dequantized
  in-VMEM after the DMA, exactly like ops/quant_matmul._qmm_kernel — the
  reference stores experts Q40 too (src/llm.cpp:425-499) and ships Q40
  slices per expert (src/nn/nn-network.cpp:856-888).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_BLOCK = 32

# Per-step VMEM budget for the three expert tiles (double-buffered by the
# pipeline; the 16 MB scoped-vmem ceiling also holds dequant temporaries).
_TILE_BUDGET_BYTES = 8_000_000


def _pick_f_block(f: int, d: int, quantized: bool, itemsize: int = 2) -> int:
    """Largest F block that divides f, satisfies Mosaic tiling for every
    operand, and fits the VMEM budget.

    The q40 variant's w2 scale tensor [E, F // 32, D] blocks its sublane
    dim at bf // 32, which Mosaic requires to be a multiple of 8 (or the
    full extent) — so quantized blocks must be multiples of 256; dense
    blocks multiples of 128. Falls back to whole-F (no blocking) when no
    multiple divides f — small test shapes take that path. `itemsize` is
    the dense weights' actual bytes/elem (the loader materializes f32/f16
    wire weights as float32, i.e. 4, not bf16's 2)."""
    # effective bytes/elem across the three tiles incl. in-kernel dequant
    # temporaries (q40: int8 + f32/32 scales + a bf16 dequant copy)
    bpe = 3.2 if quantized else float(itemsize)
    step = 256 if quantized else 128
    budget_bf = int(_TILE_BUDGET_BYTES / (2 * 3 * d * bpe))
    best = 0
    b = step
    while b <= min(f, max(budget_bf, step)):
        if f % b == 0:
            best = b
        b += step
    if best:
        return best
    if f <= max(budget_bf, step):
        return f  # small shapes: whole F fits, no blocking needed
    # no legal divisor AND whole-F busts the VMEM budget: refuse loudly
    # (callers gate on moe_pallas_supported and fall back to the dense
    # path) instead of shipping a kernel the real compiler will reject
    raise ValueError(
        f"no Mosaic-legal F block for F={f}, D={d} (need a multiple-of-"
        f"{step} divisor within the {_TILE_BUDGET_BYTES // 10**6} MB tile "
        "budget); use the dense MoE path"
    )


def moe_pallas_supported(
    d: int, f: int, quantized: bool, itemsize: int = 2
) -> bool:
    """Whether the ragged kernels can tile this expert shape inside the
    scoped-VMEM budget (transformer.forward gates the Pallas MoE path on
    this and keeps the dense path otherwise)."""
    try:
        _pick_f_block(f, d, quantized, itemsize)
        return True
    except ValueError:
        return False


def _swiglu_accum(x, w1_f, w3_f, w2_f, routing_w, ti, ki, fi, n_k, n_f,
                  acc_ref, o_ref):
    """Shared kernel tail: one F-block of SwiGLU through one expert's
    weights, weighted accumulation in VMEM scratch, row emit on the last
    (expert, F-block) step. Exact under F-blocking: silu(x@w1)*(x@w3) is
    elementwise in F and the w2 product sums over F."""

    @pl.when((ki == 0) & (fi == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h1 = jax.lax.dot_general(
        x, w1_f, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    h3 = jax.lax.dot_general(
        x, w3_f, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    hidden = (h1 / (1.0 + jnp.exp(-h1))) * h3  # silu(w1 x) * (w3 x), f32
    out = jax.lax.dot_general(
        hidden.astype(x.dtype), w2_f,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] += out * routing_w

    @pl.when((ki == n_k - 1) & (fi == n_f - 1))
    def _emit():
        o_ref[pl.ds(ti, 1), :] = acc_ref[:].astype(o_ref.dtype)


def _moe_kernel(
    idx_ref,  # scalar prefetch: [m, k] int32 expert ids
    w_ref,  # scalar prefetch: [m, k] f32 routing weights (SMEM)
    x_ref,  # [m, D] f32 (ALL token rows; whole-array block)
    w1_ref,  # [1, D, bf] (selected expert, F block)
    w3_ref,  # [1, D, bf]
    w2_ref,  # [1, bf, D]
    o_ref,  # [m, D] (whole-array block, one row written per token)
    acc_ref,  # VMEM [1, D] f32
    *,
    n_k: int,
    n_f: int,
):
    ti, ki, fi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    # dynamic sublane row: this token. x rides in f32 — an (8, 128)-tiled
    # dtype, so any row index is aligned; a bf16 x packs two rows per
    # sublane word and Mosaic demands the index be provably even. Compute
    # happens in the weights' dtype.
    x = x_ref[pl.ds(ti, 1), :].astype(w1_ref.dtype)
    _swiglu_accum(
        x, w1_ref[0], w3_ref[0], w2_ref[0],
        w_ref[ti, ki], ti, ki, fi, n_k, n_f, acc_ref, o_ref,
    )


def _dequant_block(q, d):
    """In-VMEM Q40 dequant: q int8 [I, O], d f32 [I // 32, O] -> bf16 [I, O]
    (sublane-broadcast multiply; same move as quant_matmul._qmm_kernel)."""
    i, o = q.shape
    return (
        (q.astype(jnp.float32).reshape(i // Q_BLOCK, Q_BLOCK, o) * d[:, None, :])
        .reshape(i, o)
        .astype(jnp.bfloat16)
    )


def _moe_kernel_q40(
    idx_ref,  # scalar prefetch: [m, k] int32 expert ids
    w_ref,  # scalar prefetch: [m, k] f32 routing weights
    x_ref,  # [m, D] f32 (whole-array block)
    w1q_ref,  # [1, D, bf] int8
    w1d_ref,  # [1, D // 32, bf] f32
    w3q_ref,  # [1, D, bf] int8
    w3d_ref,  # [1, D // 32, bf] f32
    w2q_ref,  # [1, bf, D] int8
    w2d_ref,  # [1, bf // 32, D] f32
    o_ref,  # [m, D] (whole-array block)
    acc_ref,  # VMEM [1, D] f32
    *,
    n_k: int,
    n_f: int,
):
    ti, ki, fi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    w1 = _dequant_block(w1q_ref[0], w1d_ref[0])
    w3 = _dequant_block(w3q_ref[0], w3d_ref[0])
    w2 = _dequant_block(w2q_ref[0], w2d_ref[0])
    x = x_ref[pl.ds(ti, 1), :].astype(jnp.bfloat16)  # f32 in: row-aligned
    _swiglu_accum(
        x, w1, w3, w2, w_ref[ti, ki], ti, ki, fi, n_k, n_f, acc_ref, o_ref
    )


def _full_map(ti, ki, fi, idx_ref, w_ref):
    # x and out ride as ONE whole-array block: a per-token (1, D) block
    # would put a size-1 dim in the last-two block dims, which Mosaic
    # rejects for m > 1 (the same tiling rule that forced the head-major
    # KV layout); rows are selected inside the kernel by dynamic sublane
    # slice instead. m is decode-lane sized, so the resident tile is tiny.
    return (0, 0)


def _row_sel_map(ti, ki, fi, idx_ref, w_ref):
    # w1/w3-shaped operands [E, D|D//32, F]: expert by routing, F by block
    return (idx_ref[ti, ki], 0, fi)


def _col_sel_map(ti, ki, fi, idx_ref, w_ref):
    # w2-shaped operands [E, F|F//32, D]: the F axis is the sublane dim
    return (idx_ref[ti, ki], fi, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def moe_active_experts(
    x: jnp.ndarray,  # [m, D] tokens (decode-sized m)
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    top_i: jnp.ndarray,  # [m, k] int32 per-token selected expert ids
    weights: jnp.ndarray,  # [m, k] f32 normalized routing weights
    interpret: bool = False,
) -> jnp.ndarray:
    """SwiGLU-MoE over exactly each token's selected experts; [m, D] f32."""
    m, d = x.shape
    e, _, f = w1.shape
    k = top_i.shape[-1]
    assert top_i.shape == (m, k), (top_i.shape, m, k)
    bf = _pick_f_block(f, d, quantized=False, itemsize=w1.dtype.itemsize)
    n_f = f // bf

    return pl.pallas_call(
        functools.partial(_moe_kernel, n_k=k, n_f=n_f),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(m, k, n_f),
            in_specs=[
                pl.BlockSpec((m, d), _full_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, bf, d), _col_sel_map),
            ],
            out_specs=pl.BlockSpec((m, d), _full_map),
            scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), jnp.float32),
        interpret=interpret,
    )(top_i, weights.astype(jnp.float32), x.astype(jnp.float32), w1, w3, w2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def moe_active_experts_q40(
    x: jnp.ndarray,  # [m, D]
    w1q: jnp.ndarray,  # [E, D, F] int8
    w1d: jnp.ndarray,  # [E, D // 32, F] f32
    w2q: jnp.ndarray,  # [E, F, D] int8
    w2d: jnp.ndarray,  # [E, F // 32, D] f32
    w3q: jnp.ndarray,  # [E, D, F] int8
    w3d: jnp.ndarray,  # [E, D // 32, F] f32
    top_i: jnp.ndarray,  # [m, k] int32
    weights: jnp.ndarray,  # [m, k] f32
    interpret: bool = False,
) -> jnp.ndarray:
    """Quantized ragged MoE: selected experts' Q40 blocks are DMA'd and
    dequantized in VMEM (0.56x the bytes of bf16 per weight — the same
    HBM-traffic win as the dense-layer Pallas matmul); [m, D] f32."""
    m, d = x.shape
    e, _, f = w1q.shape
    k = top_i.shape[-1]
    assert top_i.shape == (m, k), (top_i.shape, m, k)
    bf = _pick_f_block(f, d, quantized=True)
    n_f = f // bf

    return pl.pallas_call(
        functools.partial(_moe_kernel_q40, n_k=k, n_f=n_f),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(m, k, n_f),
            in_specs=[
                pl.BlockSpec((m, d), _full_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _row_sel_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _row_sel_map),
                pl.BlockSpec((1, bf, d), _col_sel_map),
                pl.BlockSpec((1, bf // Q_BLOCK, d), _col_sel_map),
            ],
            out_specs=pl.BlockSpec((m, d), _full_map),
            scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), jnp.float32),
        interpret=interpret,
    )(
        top_i, weights.astype(jnp.float32),
        x.astype(jnp.float32), w1q, w1d, w3q, w3d, w2q, w2d,
    )


# ---------------------------------------------------------------------------
# Grouped (prefill-scale) ragged MoE: active experts only, tokens sorted by
# expert. The decode kernels above dedicate one grid step per (token,
# choice) — fine for lane-sized m, but prefill would re-read every selected
# expert's weights per token. Here the B*T*k routing assignments are sorted
# by expert id, row-tiled at R rows, and a STATIC-size schedule (computed
# in jnp, delivered via scalar prefetch) gives each grid step one
# (row-tile, expert-segment) pair: expert weights stream once per
# overlapping tile (~once per occupied expert when tokens group well), and
# FLOPs are proportional to assignments, not to E. This is the
# megablocks-style grouped GEMM restated for Pallas-on-TPU (SURVEY.md §7's
# "MoE top-k without a dense 128-expert matmul" hard part, at prefill
# scale; reference active-only semantics: src/nn/nn-cpu-ops.cpp:1104-1136).
# ---------------------------------------------------------------------------

_GROUP_ROWS = 32  # row tile; worst-case wasted compute = E extra tiles


def _grouped_schedule(top_i, weights, n_tokens, n_experts,
                      max_segments: int | None = None):
    """jnp (traced) schedule for the grouped kernel.

    Returns (t_sorted [A_pad], w_col [A_pad, 1], step_lo/hi/tile/expert
    [G]) where A_pad pads the A = N*k sorted assignments to the row tile
    and G = A_pad/R + min(E, A) + 1 statically bounds the (tile, segment)
    pairs — every extra distinct expert inside a tile adds one step, and
    there are at most min(E, A)+1 distinct ids (incl. the padding
    sentinel). The min(E, A) term matters at DECODE scale: lane batches
    have A = m*k << E assignments, and the old E+1 bound would append ~E
    empty grid steps that each still DMA an expert tile (Mosaic does not
    elide repeated-index block loads — docs/silicon_r03.md).

    `max_segments` caps the expert-segment budget BELOW the worst case —
    the two-tier decode dedup (docs/moe_decode_dedup.md) compiles a
    small-grid variant and only dispatches it (lax.cond) when the
    runtime unique-expert count fits; with more segments than the cap
    the trailing scatter indices fall out of range and XLA drops them
    (never executed: the caller's predicate guarantees the fit)."""
    n, k = top_i.shape
    a = n * k
    r = _GROUP_ROWS
    a_pad = -(-a // r) * r
    n_tiles = a_pad // r
    seg_budget = (
        min(n_experts, a)
        if max_segments is None
        else min(n_experts, a, max_segments)
    )
    g_steps = n_tiles + seg_budget + 1

    flat_e = top_i.reshape(-1)
    flat_t = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    flat_w = weights.reshape(-1).astype(jnp.float32)
    order = jnp.argsort(flat_e, stable=True)
    e_s = jnp.concatenate(
        [flat_e[order], jnp.full((a_pad - a,), n_experts, flat_e.dtype)]
    )
    t_s = jnp.concatenate(
        [flat_t[order], jnp.zeros((a_pad - a,), jnp.int32)]
    )
    w_s = jnp.concatenate(
        [flat_w[order], jnp.zeros((a_pad - a,), jnp.float32)]
    )

    pos = jnp.arange(a_pad, dtype=jnp.int32)
    prev_e = jnp.concatenate([jnp.full((1,), -1, e_s.dtype), e_s[:-1]])
    step_start = jnp.logical_or(pos % r == 0, e_s != prev_e)
    step_id = jnp.cumsum(step_start.astype(jnp.int32)) - 1  # [a_pad]

    step_lo = jnp.full((g_steps,), a_pad, jnp.int32).at[step_id].min(pos)
    step_hi = jnp.zeros((g_steps,), jnp.int32).at[step_id].max(pos) + 1
    # empty trailing steps: lo=a_pad, hi=1 -> hi<=lo masks every row
    step_tile = jnp.clip(step_lo // r, 0, n_tiles - 1)
    step_expert = e_s[jnp.clip(step_lo, 0, a_pad - 1)]
    step_expert = jnp.clip(step_expert, 0, n_experts - 1)  # sentinel -> any
    return t_s, w_s[:, None], step_lo, step_hi, step_tile, step_expert


def _grouped_kernel(
    lo_ref, hi_ref, tile_ref, expert_ref,  # scalar prefetch [G] int32
    x_ref,  # [R, D] bf16: this tile's sorted token rows
    w_ref,  # [R, 1] f32: per-row routing weights (masked by segment here)
    w1_ref,  # [1, D, bf]
    w3_ref,  # [1, D, bf]
    w2_ref,  # [1, bf, D]
    o_ref,  # [R, D] f32
    acc_ref,  # VMEM [R, D] f32
    *,
    n_f: int,
    n_steps: int,
    rows: int,
):
    g, fi = pl.program_id(0), pl.program_id(1)
    tile = tile_ref[g]
    prev_tile = tile_ref[jnp.maximum(g - 1, 0)]
    next_tile = tile_ref[jnp.minimum(g + 1, n_steps - 1)]
    new_tile = jnp.logical_or(g == 0, tile != prev_tile)
    last_of_tile = jnp.logical_or(g == n_steps - 1, tile != next_tile)

    @pl.when(jnp.logical_and(new_tile, fi == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(w1_ref.dtype)
    h1 = jax.lax.dot_general(
        x, w1_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    h3 = jax.lax.dot_general(
        x, w3_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hidden = (h1 / (1.0 + jnp.exp(-h1))) * h3
    out = jax.lax.dot_general(
        hidden.astype(x.dtype), w2_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # rows outside this step's [lo, hi) segment belong to another expert
    # (or to padding): their routing weight is forced to 0, so the wasted
    # compute contributes exactly nothing
    row_pos = tile * rows + jax.lax.broadcasted_iota(
        jnp.int32, (rows, 1), 0
    )
    in_seg = jnp.logical_and(row_pos >= lo_ref[g], row_pos < hi_ref[g])
    w_rows = jnp.where(in_seg, w_ref[:], 0.0)
    acc_ref[:] += out * w_rows

    @pl.when(jnp.logical_and(last_of_tile, fi == n_f - 1))
    def _emit():
        o_ref[:] = acc_ref[:]


def _grouped_x_map(g, fi, lo, hi, tile, expert):
    return (tile[g], 0)


def _grouped_row_map(g, fi, lo, hi, tile, expert):
    return (tile[g], 0)


def _grouped_w13_map(g, fi, lo, hi, tile, expert):
    return (expert[g], 0, fi)


def _grouped_w2_map(g, fi, lo, hi, tile, expert):
    return (expert[g], fi, 0)


@functools.partial(
    jax.jit, static_argnames=("interpret", "max_segments")
)
def moe_grouped_experts(
    x: jnp.ndarray,  # [N, D] tokens (prefill-scale N)
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    top_i: jnp.ndarray,  # [N, k] int32
    weights: jnp.ndarray,  # [N, k] f32
    interpret: bool = False,
    max_segments: int | None = None,
) -> jnp.ndarray:
    """Grouped active-expert SwiGLU MoE; [N, D] f32. See module section
    comment: assignments sorted by expert, one grid step per (row tile,
    expert segment), expert weights streamed once per overlapping tile."""
    n, d = x.shape
    e, _, f = w1.shape
    k = top_i.shape[-1]
    bf = _pick_f_block(f, d, quantized=False, itemsize=w1.dtype.itemsize)
    n_f = f // bf
    r = _GROUP_ROWS

    t_s, w_col, lo, hi, tile, expert = _grouped_schedule(
        top_i, weights, n, e, max_segments=max_segments
    )
    a_pad = t_s.shape[0]
    g_steps = lo.shape[0]
    x_sorted = jnp.take(x, t_s, axis=0).astype(jnp.bfloat16)  # [A_pad, D]

    o_sorted = pl.pallas_call(
        functools.partial(
            _grouped_kernel, n_f=n_f, n_steps=g_steps, rows=r
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(g_steps, n_f),
            in_specs=[
                pl.BlockSpec((r, d), _grouped_x_map),
                pl.BlockSpec((r, 1), _grouped_row_map),
                pl.BlockSpec((1, d, bf), _grouped_w13_map),
                pl.BlockSpec((1, d, bf), _grouped_w13_map),
                pl.BlockSpec((1, bf, d), _grouped_w2_map),
            ],
            out_specs=pl.BlockSpec((r, d), _grouped_x_map),
            scratch_shapes=[pltpu.VMEM((r, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((a_pad, d), jnp.float32),
        interpret=interpret,
    )(lo, hi, tile, expert, x_sorted, w_col, w1, w3, w2)
    # weights ride in their NATIVE dtype — a pre-cast would materialize
    # full all-expert copies, the exact all-E HBM cost this kernel avoids;
    # the kernel casts x per tile to match instead

    # scatter-add each weighted assignment back to its token (the
    # reference's OP_SCALE + OP_MERGE_SUM combine, src/llm.cpp:489-499)
    return jnp.zeros((n, d), jnp.float32).at[t_s].add(o_sorted)


def _grouped_kernel_q40(
    lo_ref, hi_ref, tile_ref, expert_ref,  # scalar prefetch [G] int32
    x_ref,  # [R, D] bf16
    w_ref,  # [R, 1] f32
    w1q_ref,  # [1, D, bf] int8
    w1d_ref,  # [1, D // 32, bf] f32
    w3q_ref,  # [1, D, bf] int8
    w3d_ref,  # [1, D // 32, bf] f32
    w2q_ref,  # [1, bf, D] int8
    w2d_ref,  # [1, bf // 32, D] f32
    o_ref,  # [R, D] f32
    acc_ref,  # VMEM [R, D] f32
    *,
    n_f: int,
    n_steps: int,
    rows: int,
):
    g, fi = pl.program_id(0), pl.program_id(1)
    tile = tile_ref[g]
    prev_tile = tile_ref[jnp.maximum(g - 1, 0)]
    next_tile = tile_ref[jnp.minimum(g + 1, n_steps - 1)]
    new_tile = jnp.logical_or(g == 0, tile != prev_tile)
    last_of_tile = jnp.logical_or(g == n_steps - 1, tile != next_tile)

    @pl.when(jnp.logical_and(new_tile, fi == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w1 = _dequant_block(w1q_ref[0], w1d_ref[0])
    w3 = _dequant_block(w3q_ref[0], w3d_ref[0])
    w2 = _dequant_block(w2q_ref[0], w2d_ref[0])
    x = x_ref[:]
    h1 = jax.lax.dot_general(
        x, w1, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    h3 = jax.lax.dot_general(
        x, w3, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    hidden = (h1 / (1.0 + jnp.exp(-h1))) * h3
    out = jax.lax.dot_general(
        hidden.astype(x.dtype), w2,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    row_pos = tile * rows + jax.lax.broadcasted_iota(
        jnp.int32, (rows, 1), 0
    )
    in_seg = jnp.logical_and(row_pos >= lo_ref[g], row_pos < hi_ref[g])
    acc_ref[:] += out * jnp.where(in_seg, w_ref[:], 0.0)

    @pl.when(jnp.logical_and(last_of_tile, fi == n_f - 1))
    def _emit():
        o_ref[:] = acc_ref[:]


@functools.partial(
    jax.jit, static_argnames=("interpret", "max_segments")
)
def moe_grouped_experts_q40(
    x: jnp.ndarray,  # [N, D]
    w1q: jnp.ndarray,  # [E, D, F] int8
    w1d: jnp.ndarray,  # [E, D // 32, F] f32
    w2q: jnp.ndarray,  # [E, F, D] int8
    w2d: jnp.ndarray,  # [E, F // 32, D] f32
    w3q: jnp.ndarray,  # [E, D, F] int8
    w3d: jnp.ndarray,  # [E, D // 32, F] f32
    top_i: jnp.ndarray,  # [N, k] int32
    weights: jnp.ndarray,  # [N, k] f32
    interpret: bool = False,
    max_segments: int | None = None,
) -> jnp.ndarray:
    """Quantized grouped active-expert MoE (see moe_grouped_experts):
    selected experts' Q40 blocks stream once per overlapping row tile."""
    n, d = x.shape
    e, _, f = w1q.shape
    bf = _pick_f_block(f, d, quantized=True)
    n_f = f // bf
    r = _GROUP_ROWS

    t_s, w_col, lo, hi, tile, expert = _grouped_schedule(
        top_i, weights, n, e, max_segments=max_segments
    )
    a_pad = t_s.shape[0]
    g_steps = lo.shape[0]
    x_sorted = jnp.take(x, t_s, axis=0).astype(jnp.bfloat16)

    o_sorted = pl.pallas_call(
        functools.partial(
            _grouped_kernel_q40, n_f=n_f, n_steps=g_steps, rows=r
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(g_steps, n_f),
            in_specs=[
                pl.BlockSpec((r, d), _grouped_x_map),
                pl.BlockSpec((r, 1), _grouped_row_map),
                pl.BlockSpec((1, d, bf), _grouped_w13_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _grouped_w13_map),
                pl.BlockSpec((1, d, bf), _grouped_w13_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _grouped_w13_map),
                pl.BlockSpec((1, bf, d), _grouped_w2_map),
                pl.BlockSpec((1, bf // Q_BLOCK, d), _grouped_w2_map),
            ],
            out_specs=pl.BlockSpec((r, d), _grouped_x_map),
            scratch_shapes=[pltpu.VMEM((r, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((a_pad, d), jnp.float32),
        interpret=interpret,
    )(lo, hi, tile, expert, x_sorted, w_col,
      w1q, w1d, w3q, w3d, w2q, w2d)

    return jnp.zeros((n, d), jnp.float32).at[t_s].add(o_sorted)
