"""Ragged MoE dispatch: Pallas kernel computing ONLY the active experts.

The decode-path answer to SURVEY.md §7's "MoE top-k on TPU with tiny active
expert counts (A3B: 8 of 128) without wasting a dense 128-expert matmul".
The reference walks an indexes buffer and runs just the selected experts'
matmuls (src/nn/nn-cpu-ops.cpp:1104-1136); the straightforward XLA
restatement (`jnp.take` of the expert weights) measures ~3x slower than
even the dense all-expert einsum on v5e, because the gather materializes
the selected weights through HBM.

This kernel instead makes the expert id part of the DMA schedule: the
top-k indices arrive via scalar prefetch and the BlockSpec index_map picks
which expert's weight tile to copy HBM->VMEM per grid step — the selected
expert weights are read exactly once, nothing else moves.

Grid: (k,) active experts, one SwiGLU expert pipeline per step, output
accumulated in VMEM scratch weighted by the routing probabilities.
Decode-sized (B*T small); prefill keeps the dense path where every expert
is busy anyway.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _moe_kernel(
    idx_ref,  # scalar prefetch: [k] int32 expert ids
    w_ref,  # scalar prefetch: [k] f32 routing weights (SMEM)
    x_ref,  # [m, D]
    w1_ref,  # [1, D, F] (selected expert)
    w3_ref,  # [1, D, F]
    w2_ref,  # [1, F, D]
    o_ref,  # [m, D]
    acc_ref,  # VMEM [m, D] f32
    *,
    n_k: int,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]  # [m, D]
    h1 = jax.lax.dot_general(
        x, w1_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    h3 = jax.lax.dot_general(
        x, w3_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hidden = (h1 / (1.0 + jnp.exp(-h1))) * h3  # silu(w1 x) * (w3 x), f32
    out = jax.lax.dot_general(
        hidden.astype(x.dtype), w2_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] += out * w_ref[i]

    @pl.when(i == n_k - 1)
    def _emit():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def moe_active_experts(
    x: jnp.ndarray,  # [m, D] tokens (decode-sized m)
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    top_i: jnp.ndarray,  # [k] int32 selected expert ids (shared by the m tokens)
    weights: jnp.ndarray,  # [k] f32 normalized routing weights
    interpret: bool = False,
) -> jnp.ndarray:
    """SwiGLU-MoE over exactly the selected experts; returns [m, D] f32.

    Note the single shared top-k set: decode with m == 1 is the target. For
    m > 1 each token generally routes differently — that stays on the dense
    path.
    """
    m, d = x.shape
    e, _, f = w1.shape
    k = top_i.shape[0]

    def x_map(i, idx_ref, w_ref):
        return (0, 0)

    def w_sel_map(i, idx_ref, w_ref):
        return (idx_ref[i], 0, 0)

    return pl.pallas_call(
        functools.partial(_moe_kernel, n_k=k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(k,),
            in_specs=[
                pl.BlockSpec((m, d), x_map),
                pl.BlockSpec((1, d, f), w_sel_map),
                pl.BlockSpec((1, d, f), w_sel_map),
                pl.BlockSpec((1, f, d), w_sel_map),
            ],
            out_specs=pl.BlockSpec((m, d), x_map),
            scratch_shapes=[pltpu.VMEM((m, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), jnp.float32),
        interpret=interpret,
    )(top_i, weights.astype(jnp.float32), x, w1, w3, w2)
