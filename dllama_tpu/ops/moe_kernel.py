"""Ragged MoE dispatch: Pallas kernels computing ONLY the active experts.

The decode-path answer to SURVEY.md §7's "MoE top-k on TPU with tiny active
expert counts (A3B: 8 of 128) without wasting a dense 128-expert matmul".
The reference walks an indexes buffer and runs just the selected experts'
matmuls (src/nn/nn-cpu-ops.cpp:1104-1136); the straightforward XLA
restatement (`jnp.take` of the expert weights) measures ~3x slower than
even the dense all-expert einsum on v5e, because the gather materializes
the selected weights through HBM.

These kernels instead make the expert id part of the DMA schedule: the
top-k indices arrive via scalar prefetch and the BlockSpec index_map picks
which expert's weight tile to copy HBM->VMEM per grid step — the selected
expert weights are read exactly once per (token, choice), nothing else
moves.

Grid: (m, k, F blocks) — token-major, active experts next, the expert's
hidden (F) dim innermost. F-blocking is exact (SwiGLU is elementwise in F
and w2 contracts over it) and is what keeps full-scale experts (e.g. A3B:
D=2048, F=768 -> 9 MB of bf16 tiles per step unblocked) inside the 16 MB
scoped-VMEM budget with double buffering — the unblocked version was
rejected by the real compiler at exactly that shape. Routing is PER TOKEN
(each decode lane picks its own top-k, matching the reference's per-row
indexes buffer). Decode-sized m (the engine's dp lanes); prefill keeps the
dense path where every expert is busy anyway.

Two variants:
- `moe_active_experts`: dense bf16/f32 expert weights.
- `moe_active_experts_q40`: block-quantized experts (int8 values +
  per-32-block f32 scales, the `QuantWeight` device layout) dequantized
  in-VMEM after the DMA, exactly like ops/quant_matmul._qmm_kernel — the
  reference stores experts Q40 too (src/llm.cpp:425-499) and ships Q40
  slices per expert (src/nn/nn-network.cpp:856-888).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_BLOCK = 32

# Per-step VMEM budget for the three expert tiles (double-buffered by the
# pipeline; the 16 MB scoped-vmem ceiling also holds dequant temporaries).
_TILE_BUDGET_BYTES = 8_000_000


def _pick_f_block(f: int, d: int, quantized: bool, itemsize: int = 2) -> int:
    """Largest F block that divides f, satisfies Mosaic tiling for every
    operand, and fits the VMEM budget.

    The q40 variant's w2 scale tensor [E, F // 32, D] blocks its sublane
    dim at bf // 32, which Mosaic requires to be a multiple of 8 (or the
    full extent) — so quantized blocks must be multiples of 256; dense
    blocks multiples of 128. Falls back to whole-F (no blocking) when no
    multiple divides f — small test shapes take that path. `itemsize` is
    the dense weights' actual bytes/elem (the loader materializes f32/f16
    wire weights as float32, i.e. 4, not bf16's 2)."""
    # effective bytes/elem across the three tiles incl. in-kernel dequant
    # temporaries (q40: int8 + f32/32 scales + a bf16 dequant copy)
    bpe = 3.2 if quantized else float(itemsize)
    step = 256 if quantized else 128
    budget_bf = int(_TILE_BUDGET_BYTES / (2 * 3 * d * bpe))
    best = 0
    b = step
    while b <= min(f, max(budget_bf, step)):
        if f % b == 0:
            best = b
        b += step
    if best:
        return best
    if f <= max(budget_bf, step):
        return f  # small shapes: whole F fits, no blocking needed
    # no legal divisor AND whole-F busts the VMEM budget: refuse loudly
    # (callers gate on moe_pallas_supported and fall back to the dense
    # path) instead of shipping a kernel the real compiler will reject
    raise ValueError(
        f"no Mosaic-legal F block for F={f}, D={d} (need a multiple-of-"
        f"{step} divisor within the {_TILE_BUDGET_BYTES // 10**6} MB tile "
        "budget); use the dense MoE path"
    )


def moe_pallas_supported(
    d: int, f: int, quantized: bool, itemsize: int = 2
) -> bool:
    """Whether the ragged kernels can tile this expert shape inside the
    scoped-VMEM budget (transformer.forward gates the Pallas MoE path on
    this and keeps the dense path otherwise)."""
    try:
        _pick_f_block(f, d, quantized, itemsize)
        return True
    except ValueError:
        return False


def _swiglu_accum(x, w1_f, w3_f, w2_f, routing_w, ti, ki, fi, n_k, n_f,
                  acc_ref, o_ref):
    """Shared kernel tail: one F-block of SwiGLU through one expert's
    weights, weighted accumulation in VMEM scratch, row emit on the last
    (expert, F-block) step. Exact under F-blocking: silu(x@w1)*(x@w3) is
    elementwise in F and the w2 product sums over F."""

    @pl.when((ki == 0) & (fi == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h1 = jax.lax.dot_general(
        x, w1_f, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    h3 = jax.lax.dot_general(
        x, w3_f, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    hidden = (h1 / (1.0 + jnp.exp(-h1))) * h3  # silu(w1 x) * (w3 x), f32
    out = jax.lax.dot_general(
        hidden.astype(x.dtype), w2_f,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] += out * routing_w

    @pl.when((ki == n_k - 1) & (fi == n_f - 1))
    def _emit():
        o_ref[pl.ds(ti, 1), :] = acc_ref[:].astype(o_ref.dtype)


def _moe_kernel(
    idx_ref,  # scalar prefetch: [m, k] int32 expert ids
    w_ref,  # scalar prefetch: [m, k] f32 routing weights (SMEM)
    x_ref,  # [m, D] f32 (ALL token rows; whole-array block)
    w1_ref,  # [1, D, bf] (selected expert, F block)
    w3_ref,  # [1, D, bf]
    w2_ref,  # [1, bf, D]
    o_ref,  # [m, D] (whole-array block, one row written per token)
    acc_ref,  # VMEM [1, D] f32
    *,
    n_k: int,
    n_f: int,
):
    ti, ki, fi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    # dynamic sublane row: this token. x rides in f32 — an (8, 128)-tiled
    # dtype, so any row index is aligned; a bf16 x packs two rows per
    # sublane word and Mosaic demands the index be provably even. Compute
    # happens in the weights' dtype.
    x = x_ref[pl.ds(ti, 1), :].astype(w1_ref.dtype)
    _swiglu_accum(
        x, w1_ref[0], w3_ref[0], w2_ref[0],
        w_ref[ti, ki], ti, ki, fi, n_k, n_f, acc_ref, o_ref,
    )


def _dequant_block(q, d):
    """In-VMEM Q40 dequant: q int8 [I, O], d f32 [I // 32, O] -> bf16 [I, O]
    (sublane-broadcast multiply; same move as quant_matmul._qmm_kernel)."""
    i, o = q.shape
    return (
        (q.astype(jnp.float32).reshape(i // Q_BLOCK, Q_BLOCK, o) * d[:, None, :])
        .reshape(i, o)
        .astype(jnp.bfloat16)
    )


def _moe_kernel_q40(
    idx_ref,  # scalar prefetch: [m, k] int32 expert ids
    w_ref,  # scalar prefetch: [m, k] f32 routing weights
    x_ref,  # [m, D] f32 (whole-array block)
    w1q_ref,  # [1, D, bf] int8
    w1d_ref,  # [1, D // 32, bf] f32
    w3q_ref,  # [1, D, bf] int8
    w3d_ref,  # [1, D // 32, bf] f32
    w2q_ref,  # [1, bf, D] int8
    w2d_ref,  # [1, bf // 32, D] f32
    o_ref,  # [m, D] (whole-array block)
    acc_ref,  # VMEM [1, D] f32
    *,
    n_k: int,
    n_f: int,
):
    ti, ki, fi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    w1 = _dequant_block(w1q_ref[0], w1d_ref[0])
    w3 = _dequant_block(w3q_ref[0], w3d_ref[0])
    w2 = _dequant_block(w2q_ref[0], w2d_ref[0])
    x = x_ref[pl.ds(ti, 1), :].astype(jnp.bfloat16)  # f32 in: row-aligned
    _swiglu_accum(
        x, w1, w3, w2, w_ref[ti, ki], ti, ki, fi, n_k, n_f, acc_ref, o_ref
    )


def _full_map(ti, ki, fi, idx_ref, w_ref):
    # x and out ride as ONE whole-array block: a per-token (1, D) block
    # would put a size-1 dim in the last-two block dims, which Mosaic
    # rejects for m > 1 (the same tiling rule that forced the head-major
    # KV layout); rows are selected inside the kernel by dynamic sublane
    # slice instead. m is decode-lane sized, so the resident tile is tiny.
    return (0, 0)


def _row_sel_map(ti, ki, fi, idx_ref, w_ref):
    # w1/w3-shaped operands [E, D|D//32, F]: expert by routing, F by block
    return (idx_ref[ti, ki], 0, fi)


def _col_sel_map(ti, ki, fi, idx_ref, w_ref):
    # w2-shaped operands [E, F|F//32, D]: the F axis is the sublane dim
    return (idx_ref[ti, ki], fi, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def moe_active_experts(
    x: jnp.ndarray,  # [m, D] tokens (decode-sized m)
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    top_i: jnp.ndarray,  # [m, k] int32 per-token selected expert ids
    weights: jnp.ndarray,  # [m, k] f32 normalized routing weights
    interpret: bool = False,
) -> jnp.ndarray:
    """SwiGLU-MoE over exactly each token's selected experts; [m, D] f32."""
    m, d = x.shape
    e, _, f = w1.shape
    k = top_i.shape[-1]
    assert top_i.shape == (m, k), (top_i.shape, m, k)
    bf = _pick_f_block(f, d, quantized=False, itemsize=w1.dtype.itemsize)
    n_f = f // bf

    return pl.pallas_call(
        functools.partial(_moe_kernel, n_k=k, n_f=n_f),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(m, k, n_f),
            in_specs=[
                pl.BlockSpec((m, d), _full_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, bf, d), _col_sel_map),
            ],
            out_specs=pl.BlockSpec((m, d), _full_map),
            scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), jnp.float32),
        interpret=interpret,
    )(top_i, weights.astype(jnp.float32), x.astype(jnp.float32), w1, w3, w2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def moe_active_experts_q40(
    x: jnp.ndarray,  # [m, D]
    w1q: jnp.ndarray,  # [E, D, F] int8
    w1d: jnp.ndarray,  # [E, D // 32, F] f32
    w2q: jnp.ndarray,  # [E, F, D] int8
    w2d: jnp.ndarray,  # [E, F // 32, D] f32
    w3q: jnp.ndarray,  # [E, D, F] int8
    w3d: jnp.ndarray,  # [E, D // 32, F] f32
    top_i: jnp.ndarray,  # [m, k] int32
    weights: jnp.ndarray,  # [m, k] f32
    interpret: bool = False,
) -> jnp.ndarray:
    """Quantized ragged MoE: selected experts' Q40 blocks are DMA'd and
    dequantized in VMEM (0.56x the bytes of bf16 per weight — the same
    HBM-traffic win as the dense-layer Pallas matmul); [m, D] f32."""
    m, d = x.shape
    e, _, f = w1q.shape
    k = top_i.shape[-1]
    assert top_i.shape == (m, k), (top_i.shape, m, k)
    bf = _pick_f_block(f, d, quantized=True)
    n_f = f // bf

    return pl.pallas_call(
        functools.partial(_moe_kernel_q40, n_k=k, n_f=n_f),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(m, k, n_f),
            in_specs=[
                pl.BlockSpec((m, d), _full_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _row_sel_map),
                pl.BlockSpec((1, d, bf), _row_sel_map),
                pl.BlockSpec((1, d // Q_BLOCK, bf), _row_sel_map),
                pl.BlockSpec((1, bf, d), _col_sel_map),
                pl.BlockSpec((1, bf // Q_BLOCK, d), _col_sel_map),
            ],
            out_specs=pl.BlockSpec((m, d), _full_map),
            scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), jnp.float32),
        interpret=interpret,
    )(
        top_i, weights.astype(jnp.float32),
        x.astype(jnp.float32), w1q, w1d, w3q, w3d, w2q, w2d,
    )
