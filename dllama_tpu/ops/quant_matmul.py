"""Q40 weight-quantized matmul: Pallas TPU kernel + jnp reference.

The reference's hottest kernel is the Q80-activation x Q40-weight int dot
(src/nn/nn-cpu-ops.cpp:231-449). On TPU the right design is different
(SURVEY.md §7 translation table): weights stay block-quantized in HBM
(int8 values + per-32-block scales — 0.56 B/elem vs 2 for bf16) and are
dequantized INSIDE the kernel after the HBM->VMEM copy, feeding the MXU in
bf16. Decode-step matmuls are HBM-bandwidth-bound, so the ~3.6x traffic
reduction is the win; the reference's int8 activation quantization was a
CPU SIMD trick, not a quality choice, and is deliberately not reproduced
(activations ride in bf16; accumulation is f32 like the reference).

Device layout — chosen for the TPU (sublane, lane) tiling: weights are
stored TRANSPOSED relative to the `.m` file, ``q`` int8 [in, out] with the
contraction (in) axis on sublanes. The 32-element quant blocks then run
along sublanes, so the in-kernel dequant is a sublane-broadcast multiply
(a lane-dim reshape would be an unsupported Mosaic shape cast):

    w[i, o] = q[i, o] * d[i // 32, o]        # d: [in // 32, out]

and the MXU consumes ``x [m, in] @ w [in, out]`` directly, no transpose.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_BLOCK = 32


class QuantWeight(NamedTuple):
    """Planar Q40 tensor in device layout (a pytree; scan/device_put compose).

    ``q`` int8 [..., in, out] with values in [-8, 7];
    ``d`` f32 [..., in // 32, out] per-block scales (f32 holds the wire's
    f16 values exactly; bf16 would round them — scale bytes are ~2% of the
    tensor so the traffic cost is noise).
    """

    q: jnp.ndarray
    d: jnp.ndarray

    @property
    def in_dim(self) -> int:
        return self.q.shape[-2]

    @property
    def out_dim(self) -> int:
        return self.q.shape[-1]


class PackedQuantWeight(NamedTuple):
    """Packed-nibble Q40 tensor in device layout (weight_format="q40i4").

    Two int4 values per int8 byte in HBM, following the wire format's own
    intra-block pairing (formats/quants.py): within each 32-element quant
    block, byte row j holds element j in its low nibble and element j + 16
    in its high nibble. The kernel unpacks AFTER the HBM->VMEM copy
    (shift/mask, then the same sublane-broadcast scale multiply as the
    int8 path), so HBM traffic drops to what is actually stored:

    ``qp`` int8 [..., in // 2, out] packed nibble pairs;
    ``d``  f16 [..., in // 32, out] per-block scales — f16 IS the wire
    scale dtype, so packed dequant is bit-identical to the int8 path's
    (which widens the same f16 values to f32).

    0.5 + 2/32 = 0.5625 B/weight including scales, vs 1.125 for the
    unpacked QuantWeight layout — decode matmuls are HBM-bandwidth-bound,
    so this halves the weight-read floor per token.
    """

    qp: jnp.ndarray
    d: jnp.ndarray

    @property
    def in_dim(self) -> int:
        return self.qp.shape[-2] * 2

    @property
    def out_dim(self) -> int:
        return self.qp.shape[-1]


@jax.tree_util.register_pytree_node_class
class FusedQuantWeight:
    """Several row-split matmul weights fused along the out axis in
    shard-major interleaved order (models/loader._interleave_concat).

    ``fuse`` (the interleave shard count) and ``dims`` (the constituents'
    global out dims) ride as STATIC pytree aux data, so the un-interleave
    factor travels with the weights themselves — consuming fused params on
    a mesh with a different tp cannot silently mis-permute columns, and
    `lax.scan` over stacked layers preserves the metadata."""

    def __init__(self, weight: QuantWeight, fuse: int, dims: tuple[int, ...]):
        self.weight = weight
        self.fuse = int(fuse)
        self.dims = tuple(int(d) for d in dims)

    def tree_flatten(self):
        return (self.weight,), (self.fuse, self.dims)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)


def planar_to_device_layout(
    q_out_in: np.ndarray, d_out_blocks: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side layout transform from `q40_to_planar` output ([out, in]
    values, [out, in//32] f16 scales) to the kernel layout: transpose so the
    contraction axis leads, scales widened to f32."""
    q = np.ascontiguousarray(np.swapaxes(q_out_in, -1, -2))
    d = np.ascontiguousarray(np.swapaxes(d_out_blocks, -1, -2)).astype(np.float32)
    return q, d


def from_planar(q_out_in: np.ndarray, d_out_blocks: np.ndarray) -> QuantWeight:
    """Device QuantWeight from `q40_to_planar` output."""
    q, d = planar_to_device_layout(q_out_in, d_out_blocks)
    return QuantWeight(jnp.asarray(q), jnp.asarray(d))


def dequant(w: QuantWeight, dtype=jnp.bfloat16) -> jnp.ndarray:
    """[..., in, out] dense tensor (jnp reference semantics of
    nn-quants.cpp:229-246)."""
    *lead, inner, out = w.q.shape
    q = w.q.astype(jnp.float32).reshape(*lead, inner // Q_BLOCK, Q_BLOCK, out)
    dense = q * w.d.astype(jnp.float32)[..., :, None, :]
    return dense.reshape(*lead, inner, out).astype(dtype)


def pack_nibbles(w: QuantWeight) -> PackedQuantWeight:
    """Device-layout int8 QuantWeight -> packed-nibble PackedQuantWeight
    (jnp; formats.quants.pack_q40_device is the numpy twin for the load
    path). Values must already be in [-8, 7]."""
    *lead, inner, out = w.q.shape
    blk = w.q.astype(jnp.int32).reshape(
        *lead, inner // Q_BLOCK, Q_BLOCK, out
    )
    lo = blk[..., : Q_BLOCK // 2, :] + 8
    hi = blk[..., Q_BLOCK // 2 :, :] + 8
    b = lo | (hi << 4)  # [0, 255]
    qp = jnp.where(b >= 128, b - 256, b).astype(jnp.int8)
    return PackedQuantWeight(
        qp.reshape(*lead, inner // 2, out), w.d.astype(jnp.float16)
    )


def unpack_nibbles(qp: jnp.ndarray) -> jnp.ndarray:
    """Packed nibble bytes [..., in // 2, out] -> int values
    [..., in, out] int32 in [-8, 7], restoring the wire's intra-block
    (j, j + 16) pairing. Shapes stay 2D-tiled the whole way (reshape /
    concat touch the second-to-last axis only), so the same code runs
    inside the Pallas kernel's VMEM tiles."""
    *lead, half, out = qp.shape
    u = qp.astype(jnp.int32) & 0xFF
    blk = u.reshape(*lead, half // (Q_BLOCK // 2), Q_BLOCK // 2, out)
    lo = (blk & 0xF) - 8
    hi = (blk >> 4) - 8
    q = jnp.concatenate([lo, hi], axis=-2)  # [..., nb, 32, out]
    return q.reshape(*lead, half * 2, out)


def dequant_packed(w: PackedQuantWeight, dtype=jnp.bfloat16) -> jnp.ndarray:
    """[..., in, out] dense tensor from the packed-nibble layout; computes
    exactly what `dequant` computes on the unpacked equivalent (same int
    values, same f16-exact scales)."""
    *lead, half, out = w.qp.shape
    inner = half * 2
    q = unpack_nibbles(w.qp).astype(jnp.float32)
    q = q.reshape(*lead, inner // Q_BLOCK, Q_BLOCK, out)
    dense = q * w.d.astype(jnp.float32)[..., :, None, :]
    return dense.reshape(*lead, inner, out).astype(dtype)


def qmatmul_ref(x: jnp.ndarray, w) -> jnp.ndarray:
    """Reference path: dequant + dense matmul. x [..., in] -> [..., out] f32.
    Used for equivalence tests and as the off-TPU fallback. Accepts both
    QuantWeight and PackedQuantWeight."""
    if isinstance(w, PackedQuantWeight):
        dense = dequant_packed(w, jnp.float32)
    else:
        dense = dequant(w, jnp.float32)
    return jnp.einsum("...i,io->...o", x.astype(jnp.float32), dense)


def _qmm_kernel(x_ref, q_ref, d_ref, o_ref, acc_ref, *, n_k: int):
    """One (m, block_n) output tile, accumulated over k blocks in VMEM
    scratch: sublane-broadcast dequant then MXU."""
    pk = pl.program_id(1)
    q = q_ref[:]  # [bk, bn] int8
    d = d_ref[:]  # [bk // 32, bn] f32
    bk, bn = q.shape
    w = (
        (
            q.astype(jnp.float32).reshape(bk // Q_BLOCK, Q_BLOCK, bn)
            * d[:, None, :]
        )
        .reshape(bk, bn)
        .astype(jnp.bfloat16)
    )
    partial_out = jax.lax.dot_general(
        x_ref[:],
        w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pk == 0)
    def _init():
        acc_ref[:] = partial_out

    @pl.when(pk > 0)
    def _accum():
        acc_ref[:] += partial_out

    @pl.when(pk == n_k - 1)
    def _emit():
        o_ref[:] = acc_ref[:]


def _qmm_i4_kernel(x_ref, qp_ref, d_ref, o_ref, acc_ref, *, n_k: int):
    """One (m, block_n) output tile from packed-nibble weights: the
    HBM->VMEM copy moves 0.5625 B/weight, then shift/mask unpack +
    sublane-broadcast dequant in VMEM feed the MXU in bf16 exactly like
    the int8 kernel. The unpack is a handful of VPU element-ops per tile;
    the Q40 kernel was already dequant-compute-bound at 46% of HBM peak
    (docs/silicon_r03.md), so halving bytes moves the balance point, and
    the staged bench sweep (BENCH_SWEEP_FORMATS) measures which side
    wins on silicon."""
    pk = pl.program_id(1)
    qp = qp_ref[:]  # [bk // 2, bn] int8, two nibbles per byte
    d = d_ref[:]  # [bk // 32, bn] f16
    half, bn = qp.shape
    bk = half * 2
    u = qp.astype(jnp.int32) & 0xFF
    blk = u.reshape(bk // Q_BLOCK, Q_BLOCK // 2, bn)
    lo = (blk & 0xF) - 8
    hi = (blk >> 4) - 8
    w = (
        (
            jnp.concatenate([lo, hi], axis=1).astype(jnp.float32)
            * d.astype(jnp.float32)[:, None, :]
        )
        .reshape(bk, bn)
        .astype(jnp.bfloat16)
    )
    partial_out = jax.lax.dot_general(
        x_ref[:],
        w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pk == 0)
    def _init():
        acc_ref[:] = partial_out

    @pl.when(pk > 0)
    def _accum():
        acc_ref[:] += partial_out

    @pl.when(pk == n_k - 1)
    def _emit():
        o_ref[:] = acc_ref[:]


def _pick_block(n: int, preferred: int) -> int:
    """Largest 128-multiple <= preferred that divides n (vocab dims like
    151936 aren't multiples of 256)."""
    for b in range(min(preferred, n), 0, -128):
        if n % b == 0:
            return b
    return n  # fall back to a single block


@functools.partial(
    jax.jit, static_argnames=("block_n", "block_k", "interpret")
)
def qmatmul_2d(
    x: jnp.ndarray,  # [m, k]
    q: jnp.ndarray,  # [k, n] int8
    d: jnp.ndarray,  # [k // 32, n] f32
    block_n: int = 256,
    block_k: int = 4096,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas quantized matmul on 2D operands; returns [m, n] f32.

    Default blocks are the round-3 silicon sweep winner (scripts/
    kernel_sweep.py on v5e, m=1 k=4096 n=14336): (bn=256, bk=4096) ran
    0.465 ms vs 0.893 ms for the previous (512, 2048) default and 0.936 ms
    for XLA's dense bf16 matvec on the same shape — narrow n tiles with
    the whole k per step keep the accumulator live and the weight DMAs
    tall; wider tiles hit the 16 MB scoped-VMEM ceiling."""
    m, k = x.shape
    n = q.shape[1]
    assert q.shape == (k, n) and d.shape == (k // Q_BLOCK, n), (q.shape, d.shape)
    bn = _pick_block(n, block_n)
    bk = _pick_block(k, block_k)
    assert bk % Q_BLOCK == 0
    if d.dtype != jnp.float32:
        d = d.astype(jnp.float32)

    n_k = k // bk
    grid = (n // bn, n_k)  # k innermost: the accumulator tile stays live
    return pl.pallas_call(
        functools.partial(_qmm_kernel, n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, bk), lambda i, j: (0, j)),
            pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
            pl.BlockSpec((bk // Q_BLOCK, bn), lambda i, j: (j, i)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), q, d)


@functools.partial(
    jax.jit, static_argnames=("block_n", "block_k", "interpret")
)
def qmatmul_i4_2d(
    x: jnp.ndarray,  # [m, k]
    qp: jnp.ndarray,  # [k // 2, n] int8 packed nibbles
    d: jnp.ndarray,  # [k // 32, n] f16
    block_n: int = 256,
    block_k: int = 4096,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas packed-nibble quantized matmul; returns [m, n] f32.

    Same grid/accumulator structure as `qmatmul_2d` (k innermost so the
    output tile stays live in VMEM scratch); the weight BlockSpec moves
    half the rows because each byte carries two values. Block defaults
    inherit the int8 sweep winner — at equal (bn, bk) the packed DMA is
    half the bytes, so the VMEM ceiling moves further out, and the
    staged silicon sweep re-tunes on hardware."""
    m, k = x.shape
    n = qp.shape[1]
    assert qp.shape == (k // 2, n) and d.shape == (k // Q_BLOCK, n), (
        qp.shape,
        d.shape,
    )
    bn = _pick_block(n, block_n)
    bk = _pick_block(k, block_k)
    assert bk % Q_BLOCK == 0

    n_k = k // bk
    grid = (n // bn, n_k)
    return pl.pallas_call(
        functools.partial(_qmm_i4_kernel, n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, bk), lambda i, j: (0, j)),
            pl.BlockSpec((bk // 2, bn), lambda i, j: (j, i)),
            pl.BlockSpec((bk // Q_BLOCK, bn), lambda i, j: (j, i)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), qp, d)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def qmatmul(x: jnp.ndarray, w, block_n: int = 256) -> jnp.ndarray:
    """x [..., in] @ W -> [..., out] f32, auto-flattening leading dims.

    Accepts QuantWeight (int8 values) or PackedQuantWeight (nibble-packed).
    Dispatches to the matching Pallas kernel on TPU; off-TPU (CPU test
    meshes) uses the dequant reference path — pallas interpret mode is
    orders of magnitude slower and numerically identical anyway.
    """
    *lead, k = x.shape
    if not _use_pallas():
        return qmatmul_ref(x, w)
    m = 1
    for s in lead:
        m *= s
    if isinstance(w, PackedQuantWeight):
        out = qmatmul_i4_2d(x.reshape(m, k), w.qp, w.d, block_n=block_n)
    else:
        out = qmatmul_2d(x.reshape(m, k), w.q, w.d, block_n=block_n)
    return out.reshape(*lead, w.out_dim)


def qmatmul_tp(
    x: jnp.ndarray,  # [B, T, in]
    w,  # QuantWeight | PackedQuantWeight [in, out] (+ scales), tp-shardable
    role: str,  # "row" (out split) | "col" (in split, partial-sum psum)
    mesh=None,
    sync_quant: bool = False,  # Q80-compress the col-split partial-sum
    #   all-reduce payload (the reference's --buffer-float-type q80; see
    #   parallel/collectives.psum_q80) — for DCN multi-host, not ICI
) -> jnp.ndarray:
    """Tensor-parallel quantized matmul.

    GSPMD cannot partition a `pallas_call`, so on a multi-device mesh the
    kernel runs per-shard under `shard_map` with the TP layout made
    explicit — the manual-collective restatement of the reference's design:
    row-split needs no collective (the all-gather the reference does per
    block is deferred to the residual psum), col-split partial sums psum
    over ICI exactly where the reference ran SYNC_NODE_SLICES + OP_MERGE_ADD
    (src/llm.cpp:403,554).

    Off TPU this degrades to the dequant einsum and lets GSPMD shard it.
    """
    if not _use_pallas():
        return qmatmul_ref(x, w)
    if mesh is None or mesh.devices.size == 1:
        return qmatmul(x, w)

    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map_compat

    # both weight classes are (values, scales) NamedTuples whose leaves
    # shard identically: the packed in/2 axis and the in/32 scale axis
    # both divide by tp under the engine's 32*tp divisibility check
    cls = type(w)
    values, scales = w

    if role == "row":
        in_specs = (
            P("dp", None, None),
            P(None, "tp"),
            P(None, "tp"),
        )
        out_spec = P("dp", None, "tp")

        def f(xx, qq, dd):
            return qmatmul(xx, cls(qq, dd))

    elif role == "col":
        from ..parallel.collectives import psum_maybe_quantized

        in_specs = (
            P("dp", None, "tp"),
            P("tp", None),
            P("tp", None),
        )
        out_spec = P("dp", None, None)

        def f(xx, qq, dd):
            return psum_maybe_quantized(
                qmatmul(xx, cls(qq, dd)), "tp", sync_quant
            )

    else:
        raise ValueError(f"unknown role: {role}")

    return shard_map_compat(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_spec, check_vma=False
    )(x, values, scales)
