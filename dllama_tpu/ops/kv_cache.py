"""Quantized KV-cache container and helpers (shared by models and ops).

Lives in ops/ (not models/) so the Pallas attention kernels can consume a
QuantKV natively without a models<->ops import cycle: the int8-KV flash
prefill (VERDICT r4 #3) passes the int8 values and per-row scales straight
into the kernel instead of materializing a dense bf16 view of the cache.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class QuantKV(NamedTuple):
    """int8 KV cache tensor: per-row (position) symmetric quantization.

    ``q`` int8 [..., S, hd]; ``s`` f32 [..., S, 1] per-row scales. The
    trailing singleton keeps the scale tensor the same RANK as the
    values, so every positional write strategy (plain / cyclic-sp /
    owning-shard window) and every PartitionSpec applies to both leaves
    unchanged. The flash prefill kernels consume the pair natively (the
    scale rides as a second [bs, 1]-blocked ref sharing the kv index
    map; dequant happens on the VMEM tile after the DMA — so prefill
    reads int8 bytes, not a materialized dense copy); the windowed
    decode read dequants in XLA, fused into the attention dot. Halves
    KV HBM vs bf16 (+1/(2*hd) scale overhead): the long-context fit
    lever on top of the windowed reads."""

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):  # value-tensor shape: callers index S via shape[i]
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


def quantize_kv_rows(val: jnp.ndarray):
    """[..., T, hd] -> (int8 values, f32 [..., T, 1] scales): the shared
    grouped symmetric quantizer (ops/int8_matmul.quantize_acts — the Q80
    move) with one group per cache row, so the KV path and the int8
    matmul path cannot drift."""
    from .int8_matmul import quantize_acts

    return quantize_acts(val.astype(jnp.float32), val.shape[-1])


def dequant_kv(cache_l, dtype):
    """Dense view of a cache leaf: QuantKV -> values * scales (XLA
    fuses this into the consuming attention dot on the decode path);
    plain arrays pass through."""
    if isinstance(cache_l, QuantKV):
        return (cache_l.q.astype(jnp.float32) * cache_l.s).astype(dtype)
    return cache_l


def slice_kv(cache_l, w: int):
    """Sequence-axis prefix slice of a cache leaf ([B, KH, S, hd] layout),
    QuantKV-aware; w == 0 means the full view."""
    if not w:
        return cache_l
    if isinstance(cache_l, QuantKV):
        return QuantKV(cache_l.q[:, :, :w], cache_l.s[:, :, :w])
    return cache_l[:, :, :w]


def gather_pages(pool_l, page_ids):
    """Contiguous read view of a paged pool leaf.

    ``pool_l`` [P, KH, ps, hd] (one layer of the engine's page pool, or a
    QuantKV pair of [P, KH, ps, hd] values + [P, KH, ps, 1] scales) and
    ``page_ids`` [n] int32 -> [KH, n*ps, hd] rows in page order, the
    head-major layout every attention path consumes."""
    if isinstance(pool_l, QuantKV):
        return QuantKV(
            gather_pages(pool_l.q, page_ids), gather_pages(pool_l.s, page_ids)
        )
    pages = pool_l[page_ids]  # [n, KH, ps, last]
    n, kh, ps, last = pages.shape
    return pages.transpose(1, 0, 2, 3).reshape(kh, n * ps, last)


def scatter_pages(pool_l, page_ids, rows):
    """Write contiguous rows back into pool pages (inverse of
    :func:`gather_pages`): ``rows`` [KH, n*ps, hd] lands in ``pool_l``
    [P, KH, ps, hd] at ``page_ids`` [n]. QuantKV-aware on both sides."""
    if isinstance(pool_l, QuantKV):
        return QuantKV(
            scatter_pages(pool_l.q, page_ids, rows.q),
            scatter_pages(pool_l.s, page_ids, rows.s),
        )
    kh, _, last = rows.shape
    ps = pool_l.shape[2]
    n = page_ids.shape[0]
    pages = rows.reshape(kh, n, ps, last).transpose(1, 0, 2, 3)
    return pool_l.at[page_ids].set(pages.astype(pool_l.dtype))


def paged_view(pool_l, page_ids, dtype):
    """Dense [KH, n*ps, hd] view of the given pages, dequantized when the
    pool stores QuantKV — the read path for code that wants contiguous
    rows without caring how the pool stores them."""
    return dequant_kv(gather_pages(pool_l, page_ids), dtype)
