"""Grouped-int8 matmul: native MXU integer dots for Q40 checkpoints.

Round-3 silicon showed the shipping Q40 kernel is DEQUANT-compute-bound,
not DMA-bound: per-element int8->float conversion + sublane-broadcast
multiply on the VPU costs more than the HBM reads it saves (the kernel
realizes ~46% of HBM peak vs 67% for XLA dense bf16; the r3 sweep's
"int8-raw" probe, which measured the convert alone, ran 1.01 ms vs
0.47 ms for the full kernel — docs/silicon_r03.md). The fix is the
reference's own arithmetic (src/nn/nn-cpu-ops.cpp:231-449: Q80
activations x Q40 weights in INTEGER dot products, scales applied to the
block sums) restated for the MXU:

  * weights are REQUANTIZED once at load from Q40 (int4 values, per-32
    f16 scales — a CPU SIMD layout) to int8 values with per-(G, column)
    scales, G rows per group (default 512). int8 is the MXU's native
    low-precision input; the 16x coarser scale granularity is repaid by
    int8's 16x finer step (per-32 int4 step = d; per-512 int8 step =
    max_group|w|/127 <= 8*max_d/127 ~= d_max/16), so requantization adds
    less error than Q40 itself carries whenever a column's scales vary
    by < ~16x within a group.
  * activations are quantized per-(row, G-group) to int8 on the fly
    (XLA ops, fused into the preceding norm) — the Q80 analogue with
    group-sized blocks so the scale factors out of each MXU dot.
  * the kernel computes int8 x int8 -> int32 `lax.dot_general`s per
    G-slice — NO per-element dequant work at all — and applies
    sx[m,g] * sw[g,n] to the [m, bn] group sums in f32.

HBM traffic per weight: 1 byte + 4/G scale (~1.008 at G=512) vs 1.125
for the Q40 layout and 2.0 for bf16.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .quant_matmul import QuantWeight, _pick_block, dequant


class Int8Weight(NamedTuple):
    """Grouped-int8 tensor in device layout (a pytree).

    ``q`` int8 [..., k, n] values in [-127, 127];
    ``s`` f32 [..., k // G, n] per-(group, column) scales. The group size
    G rides implicitly as ``k // s.shape[-2]`` so the pytree stays
    two-leaf and scan/device_put compose.
    """

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def in_dim(self) -> int:
        return self.q.shape[-2]

    @property
    def out_dim(self) -> int:
        return self.q.shape[-1]

    @property
    def group(self) -> int:
        return self.q.shape[-2] // self.s.shape[-2]


def requantize_q40(w: QuantWeight, group: int = 512) -> Int8Weight:
    """One-time load transform Q40 -> grouped int8 (see module docstring).

    Works on stacked [..., k, n] tensors. jit-safe; runs on-device at
    load so an 8B checkpoint requantizes in seconds.
    """
    k = w.in_dim
    if k % group != 0:
        raise ValueError(f"k={k} not divisible by group={group}")
    dense = dequant(w, jnp.float32)  # [..., k, n]
    *lead, _, n = dense.shape
    g = dense.reshape(*lead, k // group, group, n)
    s = jnp.max(jnp.abs(g), axis=-2) / 127.0  # [..., k//G, n]
    s = jnp.where(s == 0, 1.0, s)
    qi = jnp.clip(jnp.round(g / s[..., :, None, :]), -127, 127).astype(jnp.int8)
    return Int8Weight(qi.reshape(*lead, k, n), s)


def quantize_acts(x: jnp.ndarray, group: int):
    """Per-(row, G-group) int8 activation quantization: the Q80 step
    (reference: quantizeQ80Row) with group-sized blocks. Returns
    (xq int8 [..., k], sx f32 [..., k//G])."""
    *lead, k = x.shape
    if k % group != 0:
        raise ValueError(f"k={k} not divisible by group={group}")
    g = x.astype(jnp.float32).reshape(*lead, k // group, group)
    sx = jnp.max(jnp.abs(g), axis=-1) / 127.0
    sx = jnp.where(sx == 0, 1.0, sx)
    xq = jnp.clip(jnp.round(g / sx[..., None]), -127, 127).astype(jnp.int8)
    return xq.reshape(*lead, k), sx


def i8matmul_ref(x: jnp.ndarray, w: Int8Weight) -> jnp.ndarray:
    """Reference path (exact integer semantics of the kernel): quantize
    activations, integer dots per group, scale the group sums. Off-TPU
    fallback and the tests' oracle."""
    group = w.group
    *lead, k = x.shape
    m = int(np.prod(lead, dtype=np.int64)) if lead else 1
    xq, sx = quantize_acts(x.reshape(m, k), group)
    n = w.out_dim
    ng = k // group
    xg = xq.astype(jnp.int32).reshape(m, ng, group)
    qg = w.q.astype(jnp.int32).reshape(ng, group, n)
    idot = jnp.einsum("mgk,gkn->mgn", xg, qg)  # int32 group sums
    out = jnp.einsum(
        "mgn,mg,gn->mn", idot.astype(jnp.float32), sx, w.s.astype(jnp.float32)
    )
    return out.reshape(*lead, n)


def _i8mm_kernel(xq_ref, sx_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int,
                 group: int):
    """One (m, bn) output tile accumulated over k blocks: per G-slice
    native int8 MXU dots, scales applied to the [m, bn] group sums."""
    pk = pl.program_id(1)
    bk = xq_ref.shape[1]
    m = xq_ref.shape[0]
    partial_out = jnp.zeros((m, o_ref.shape[1]), jnp.float32)
    for g in range(bk // group):
        idot = lax.dot_general(
            xq_ref[:, g * group : (g + 1) * group],
            q_ref[g * group : (g + 1) * group, :],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        scale = sx_ref[:, g][:, None] * s_ref[g, :][None, :]
        partial_out = partial_out + idot.astype(jnp.float32) * scale

    @pl.when(pk == 0)
    def _init():
        acc_ref[:] = partial_out

    @pl.when(pk > 0)
    def _accum():
        acc_ref[:] += partial_out

    @pl.when(pk == n_k - 1)
    def _emit():
        o_ref[:] = acc_ref[:]


@functools.partial(
    jax.jit, static_argnames=("block_n", "block_k", "interpret")
)
def i8matmul_2d(
    xq: jnp.ndarray,  # [m, k] int8
    sx: jnp.ndarray,  # [m, k // G] f32
    q: jnp.ndarray,  # [k, n] int8
    s: jnp.ndarray,  # [k // G, n] f32
    block_n: int = 256,
    block_k: int = 4096,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas grouped-int8 matmul; returns [m, n] f32.

    Default blocks inherit the Q40 sweep winner (bn=256, bk=4096) as the
    starting point; scripts/sweep_r04_i8.py re-sweeps on silicon."""
    m, k = xq.shape
    n = q.shape[1]
    ng = s.shape[0]
    assert k % ng == 0, (k, ng)
    group = k // ng
    assert q.shape == (k, n) and sx.shape == (m, ng), (q.shape, sx.shape)
    bn = _pick_block(n, block_n)
    # The k block must divide k AND hold whole groups; search downward over
    # group multiples for a divisor of k (group itself always qualifies:
    # pick_group guarantees group | k).
    bk = next(
        b
        for b in range(max(group, min(block_k, k) // group * group), 0, -group)
        if k % b == 0
    )
    assert k % bk == 0 and bk % group == 0, (k, bk, group)
    if s.dtype != jnp.float32:
        s = s.astype(jnp.float32)

    n_k = k // bk
    grid = (n // bn, n_k)  # k innermost: the accumulator tile stays live
    return pl.pallas_call(
        functools.partial(_i8mm_kernel, n_k=n_k, group=group),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, bk), lambda i, j: (0, j)),
            pl.BlockSpec((m, bk // group), lambda i, j: (0, j)),
            pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
            pl.BlockSpec((bk // group, bn), lambda i, j: (j, i)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        interpret=interpret,
    )(xq, sx, q, s)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def i8matmul(x: jnp.ndarray, w: Int8Weight, block_n: int = 256) -> jnp.ndarray:
    """x [..., in] @ W -> [..., out] f32, auto-flattening leading dims.
    Quantizes activations per group on the fly (XLA, fuses into the
    preceding ops), then dispatches to the Pallas kernel on TPU; off-TPU
    uses the exact-integer reference path."""
    if not _use_pallas():
        return i8matmul_ref(x, w)
    *lead, k = x.shape
    m = 1
    for d in lead:
        m *= d
    xq, sx = quantize_acts(x.reshape(m, k), w.group)
    out = i8matmul_2d(xq, sx, w.q, w.s, block_n=block_n)
    return out.reshape(*lead, w.out_dim)


def i8matmul_tp(
    x: jnp.ndarray,  # [B, T, in]
    w: Int8Weight,  # [in, out] (+ grouped scales), possibly tp-sharded
    role: str,  # "row" (out split) | "col" (in split, partial-sum psum)
    mesh=None,
    sync_quant: bool = False,
) -> jnp.ndarray:
    """Tensor-parallel grouped-int8 matmul — same collective layout as
    quant_matmul.qmatmul_tp (row split: no collective; col split: psum
    where the reference ran SYNC_NODE_SLICES + OP_MERGE_ADD). Activation
    quantization happens INSIDE the shard body on the local x slice, so
    col-split groups align with the shard's own scale rows."""
    if not _use_pallas():
        return i8matmul_ref(x, w)
    if mesh is None or mesh.devices.size == 1:
        return i8matmul(x, w)

    from ..utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    if role == "row":
        in_specs = (
            P("dp", None, None),
            P(None, "tp"),
            P(None, "tp"),
        )
        out_spec = P("dp", None, "tp")

        def f(xx, qq, ss):
            return i8matmul(xx, Int8Weight(qq, ss))

    elif role == "col":
        from ..parallel.collectives import psum_maybe_quantized

        in_specs = (
            P("dp", None, "tp"),
            P("tp", None),
            P("tp", None),
        )
        out_spec = P("dp", None, None)

        def f(xx, qq, ss):
            return psum_maybe_quantized(
                i8matmul(xx, Int8Weight(qq, ss)), "tp", sync_quant
            )

    else:
        raise ValueError(f"unknown role: {role}")

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_spec, check_vma=False
    )(x, w.q, w.s)


def requantize_q40_stacked(w: QuantWeight, group: int = 512) -> Int8Weight:
    """Layer-stacked [L, k, n] requantization with bounded transient
    memory: `lax.map` processes one layer at a time, so the f32 dequant
    scratch peaks at one layer's [k, n] instead of the whole stack (an
    8B w13 stack would need ~15 GB at once)."""
    if w.q.ndim == 2:
        return jax.jit(requantize_q40, static_argnames=("group",))(
            w, group=group
        )
    return lax.map(
        lambda wl: requantize_q40(wl, group), w
    )


def pick_group(h, tp: int, preferred: int = 512) -> int:
    """Largest group <= preferred dividing every PER-SHARD contraction
    dim (row matmuls contract over the full `dim`; col splits contract
    over q_dim/tp and ff_dim/tp locally), so scale rows tile both the
    weight shards and the kernel's k blocks."""
    import math

    dims = [h.dim, h.q_dim // tp, h.ff_dim // tp]
    g = math.gcd(*dims)
    group = min(preferred, g)
    while group > 1 and any(d % group for d in dims):
        group //= 2
    if group < 32:
        raise ValueError(
            f"no viable int8 group for dims {dims} (gcd {g}); "
            "use weight_format='q40'"
        )
    return group


def requantize_params(params: dict, h, group: int) -> dict:
    """Load-time transform of a q40 params tree to grouped int8: every
    attention/FFN/vocab QuantWeight becomes an Int8Weight (fused wrappers
    keep their interleave metadata). MoE EXPERT tensors stay Q40 — the
    ragged/grouped MoE kernels consume Q40 blocks natively and their
    active-expert DMA schedule is the win there."""
    from .quant_matmul import FusedQuantWeight

    moe = bool(getattr(h, "n_experts", 0))

    def conv(v, name: str):
        if isinstance(v, FusedQuantWeight):
            return FusedQuantWeight(
                requantize_q40_stacked(v.weight, group), v.fuse, v.dims
            )
        if isinstance(v, QuantWeight):
            if moe and name in ("w1", "w2", "w3"):
                return v  # expert tensors stay q40 for the MoE kernels
            return requantize_q40_stacked(v, group)
        return v

    out = dict(params)
    out["layers"] = {
        k: conv(v, k) for k, v in params["layers"].items()
    }
    if isinstance(params.get("wcls"), QuantWeight):
        out["wcls"] = requantize_q40_stacked(params["wcls"], group)
    return out
