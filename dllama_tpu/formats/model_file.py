"""Reader for distributed-llama's `.m` model file format.

Format (reference: src/llm.cpp:36-116, converter/writer.py:109-148):

    int32 magic = 0xA00ABCD
    int32 headerSize          # bytes, counting magic+headerSize themselves
    int32 key, int32 value    # repeated; keys from LlmHeaderKey (src/llm.hpp:8-31)
    ...tensor data...         # fixed order, see `tensor_plan`

Quirks faithfully reproduced:
  * float-valued header fields (rope theta, rope scaling factors) are stored
    as ints and cast (src/llm.cpp:86-91) — only integer values survive;
  * norm epsilon is an enum: 5 -> 1e-5, 6 -> 1e-6 (src/llm.cpp:30-34);
  * ``head_dim`` defaults to dim/nHeads when absent (src/llm.cpp:106-108);
  * Qwen3 / Qwen3-MoE force Falcon (half-rotation) RoPE (src/llm.cpp:113-114).

The tensor section is walked lazily via a single ``np.memmap``; per-tensor
views are zero-copy, so a 40 GB 70B file never materializes on host. The
tensor order matches the converter exactly (converter/convert-hf.py:59-104)
which is the same order `loadLlmNetWeight` consumes (src/llm.cpp:614-669).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
from typing import Iterator

import numpy as np

from .quants import (
    FloatType,
    dequantize_q40,
    dequantize_q80,
    q40_to_planar,
    tensor_bytes,
)

MODEL_MAGIC = 0x0A00ABCD
_OLD_MAGICS = (0xABCD00, 0xABCD01)


class LlmArch(enum.IntEnum):
    """Model architectures (reference: src/llm.hpp:38-42)."""

    LLAMA = 0xABCD00
    QWEN3 = 0xABCD01
    QWEN3_MOE = 0xABCD02


class RopeType(enum.IntEnum):
    """RoPE variants (reference: src/nn/nn-core.hpp:125-129)."""

    LLAMA = 0  # interleaved pairs (x[2i], x[2i+1])
    FALCON = 1  # half-rotation (x[j], x[j + headDim/2])
    LLAMA3_1 = 2  # interleaved + llama-3.1 frequency scaling


class HiddenAct(enum.IntEnum):
    """FFN activation (reference: src/llm.hpp:33-36)."""

    GELU = 0
    SILU = 1


class HeaderKey(enum.IntEnum):
    """`.m` header keys (reference: src/llm.hpp:8-31)."""

    VERSION = 0
    ARCH_TYPE = 1
    DIM = 2
    HIDDEN_DIM = 3
    N_LAYERS = 4
    N_HEADS = 5
    N_KV_HEADS = 6
    N_EXPERTS = 7
    N_ACTIVE_EXPERTS = 8
    VOCAB_SIZE = 9
    SEQ_LEN = 10
    HIDDEN_ACT = 11
    ROPE_THETA = 12
    WEIGHT_FLOAT_TYPE = 13
    ROPE_SCALING_FACTOR = 14
    ROPE_SCALING_LOW_FREQ_FACTOR = 15
    ROPE_SCALING_HIGH_FREQ_FACTORY = 16
    ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
    ROPE_TYPE = 18
    HEAD_DIM = 19
    NORM_EPSILON = 20
    MOE_HIDDEN_DIM = 21


@dataclasses.dataclass
class LlmHeader:
    """Parsed `.m` header (mirror of reference LlmHeader, src/llm.hpp:44-74)."""

    version: int = 0
    arch: LlmArch = LlmArch.LLAMA
    dim: int = 0
    hidden_dim: int = 0
    n_layers: int = 0
    n_heads: int = 0
    n_kv_heads: int = 0
    n_experts: int = 0
    n_active_experts: int = 0
    vocab_size: int = 0
    orig_seq_len: int = 0
    seq_len: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_theta: float = 10000.0
    weight_type: FloatType = FloatType.Q40
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    rope_type: RopeType = RopeType.LLAMA
    head_dim: int = 0
    norm_epsilon: float = 1e-5
    moe_hidden_dim: int = 0
    header_bytes: int = 0
    file_size: int = 0
    sync_type: FloatType = FloatType.Q80

    @property
    def q_dim(self) -> int:
        return self.head_dim * self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.head_dim * self.n_kv_heads

    @property
    def ff_dim(self) -> int:
        """Per-expert (MoE) or dense FFN intermediate dim (src/llm.cpp:152-157)."""
        if self.arch == LlmArch.QWEN3_MOE:
            return self.moe_hidden_dim
        return self.hidden_dim

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


def _norm_epsilon(value: int) -> float:
    if value == 5:
        return 1e-5
    if value == 6:
        return 1e-6
    raise ValueError(f"unsupported norm epsilon enum: {value}")


def read_llm_header(
    path: str, max_seq_len: int = 0, sync_type: FloatType = FloatType.Q80
) -> LlmHeader:
    """Parse the `.m` header (reference: src/llm.cpp:36-116)."""
    h = LlmHeader()
    with open(path, "rb") as f:
        (magic,) = struct.unpack("<i", f.read(4))
        if magic in _OLD_MAGICS:
            raise ValueError("old model format is not supported")
        if magic != MODEL_MAGIC:
            raise ValueError(f"unsupported magic number: {magic:#x}")
        (header_size,) = struct.unpack("<i", f.read(4))
        n_kv_bytes = header_size - 8
        buf = f.read(n_kv_bytes)
        values = struct.unpack(f"<{n_kv_bytes // 4}i", buf)
        weight_type = None
        for key, value in zip(values[0::2], values[1::2]):
            key = HeaderKey(key)
            if key == HeaderKey.VERSION:
                h.version = value
            elif key == HeaderKey.ARCH_TYPE:
                h.arch = LlmArch(value)
            elif key == HeaderKey.DIM:
                h.dim = value
            elif key == HeaderKey.HIDDEN_DIM:
                h.hidden_dim = value
            elif key == HeaderKey.N_LAYERS:
                h.n_layers = value
            elif key == HeaderKey.N_HEADS:
                h.n_heads = value
            elif key == HeaderKey.N_KV_HEADS:
                h.n_kv_heads = value
            elif key == HeaderKey.N_EXPERTS:
                h.n_experts = value
            elif key == HeaderKey.N_ACTIVE_EXPERTS:
                h.n_active_experts = value
            elif key == HeaderKey.VOCAB_SIZE:
                h.vocab_size = value
            elif key == HeaderKey.SEQ_LEN:
                h.seq_len = value
            elif key == HeaderKey.HIDDEN_ACT:
                h.hidden_act = HiddenAct(value)
            elif key == HeaderKey.ROPE_THETA:
                h.rope_theta = float(value)
            elif key == HeaderKey.WEIGHT_FLOAT_TYPE:
                weight_type = FloatType(value)
            elif key == HeaderKey.ROPE_SCALING_FACTOR:
                h.rope_scaling_factor = float(value)
            elif key == HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR:
                h.rope_scaling_low_freq_factor = float(value)
            elif key == HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTORY:
                h.rope_scaling_high_freq_factor = float(value)
            elif key == HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN:
                h.rope_scaling_orig_max_seq_len = value
            elif key == HeaderKey.ROPE_TYPE:
                h.rope_type = RopeType(value)
            elif key == HeaderKey.HEAD_DIM:
                h.head_dim = value
            elif key == HeaderKey.NORM_EPSILON:
                h.norm_epsilon = _norm_epsilon(value)
            elif key == HeaderKey.MOE_HIDDEN_DIM:
                h.moe_hidden_dim = value

        if weight_type is None:
            raise ValueError("model does not specify weight type")
        h.weight_type = weight_type
        h.header_bytes = header_size
        f.seek(0, 2)
        h.file_size = f.tell()

    h.orig_seq_len = h.seq_len
    if max_seq_len > 0 and h.seq_len > max_seq_len:
        h.seq_len = max_seq_len
    if h.head_dim == 0:
        h.head_dim = h.dim // h.n_heads
    h.sync_type = sync_type
    if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        h.rope_type = RopeType.FALCON
    return h


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """One tensor's location inside the `.m` file."""

    name: str
    float_type: FloatType
    shape: tuple[int, ...]  # row-major, HF convention: (out_features, in_features)
    offset: int  # absolute byte offset in the file
    nbytes: int

    @property
    def n_elements(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


def tensor_plan(h: LlmHeader) -> list[TensorSpec]:
    """The fixed tensor order of a `.m` file.

    Mirrors converter/convert-hf.py:59-104 (writer side) and
    src/llm.cpp:614-669 (reader side). Shapes are (out, in) row-major as
    exported from HF safetensors.
    """
    specs: list[TensorSpec] = []
    offset = h.header_bytes  # header_bytes counts magic+headerSize+kv data
    wt = h.weight_type

    def add(name: str, ft: FloatType, shape: tuple[int, ...]) -> None:
        nonlocal offset
        n = 1
        for s in shape:
            n *= s
        nbytes = tensor_bytes(ft, n)
        specs.append(TensorSpec(name, ft, shape, offset, nbytes))
        offset += nbytes

    add("embed", FloatType.F32, (h.vocab_size, h.dim))
    for l in range(h.n_layers):
        add(f"layers.{l}.q", wt, (h.q_dim, h.dim))
        add(f"layers.{l}.k", wt, (h.kv_dim, h.dim))
        add(f"layers.{l}.v", wt, (h.kv_dim, h.dim))
        add(f"layers.{l}.wo", wt, (h.dim, h.q_dim))
        if h.n_experts > 0:
            add(f"layers.{l}.moe_gate", FloatType.F32, (h.n_experts, h.dim))
            for e in range(h.n_experts):
                add(f"layers.{l}.experts.{e}.w1", wt, (h.ff_dim, h.dim))
                add(f"layers.{l}.experts.{e}.w2", wt, (h.dim, h.ff_dim))
                add(f"layers.{l}.experts.{e}.w3", wt, (h.ff_dim, h.dim))
        else:
            add(f"layers.{l}.w1", wt, (h.ff_dim, h.dim))
            add(f"layers.{l}.w2", wt, (h.dim, h.ff_dim))
            add(f"layers.{l}.w3", wt, (h.ff_dim, h.dim))
        if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
            add(f"layers.{l}.q_norm", FloatType.F32, (h.head_dim,))
            add(f"layers.{l}.k_norm", FloatType.F32, (h.head_dim,))
        add(f"layers.{l}.att_norm", FloatType.F32, (h.dim,))
        add(f"layers.{l}.ffn_norm", FloatType.F32, (h.dim,))
    add("final_norm", FloatType.F32, (h.dim,))
    add("wcls", wt, (h.vocab_size, h.dim))
    return specs


class ModelReader:
    """Lazy reader over a `.m` file's tensor section.

    Uses a read-only memmap (TPU-native analogue of the reference's
    mmap + slice-by-slice streaming weight loader, src/mmap.hpp +
    src/llm.cpp:614-669): tensors are materialized one at a time, so peak
    host memory stays at one tensor regardless of model size.
    """

    def __init__(self, path: str, max_seq_len: int = 0):
        self.path = path
        self.header = read_llm_header(path, max_seq_len=max_seq_len)
        self.specs = tensor_plan(self.header)
        self.by_name = {s.name: s for s in self.specs}
        expected_end = self.specs[-1].offset + self.specs[-1].nbytes
        if expected_end != self.header.file_size:
            raise ValueError(
                f"model file size mismatch: expected {expected_end} bytes, "
                f"file has {self.header.file_size} (wrong arch/config?)"
            )
        self._mmap = np.memmap(path, dtype=np.uint8, mode="r")

    def raw(self, name: str) -> np.ndarray:
        """Zero-copy packed bytes of a tensor."""
        s = self.by_name[name]
        return self._mmap[s.offset : s.offset + s.nbytes]

    def dense_f32(self, name: str) -> np.ndarray:
        """Tensor dequantized to f32, in its file shape."""
        s = self.by_name[name]
        raw = self.raw(name)
        if s.float_type == FloatType.F32:
            out = raw.view(np.float32).copy()
        elif s.float_type == FloatType.F16:
            out = raw.view(np.float16).astype(np.float32)
        elif s.float_type == FloatType.Q40:
            out = dequantize_q40(raw, s.n_elements)
        elif s.float_type == FloatType.Q80:
            out = dequantize_q80(raw, s.n_elements)
        else:
            raise ValueError(f"unsupported float type: {s.float_type}")
        return out.reshape(s.shape)

    def planar_q40_range(
        self, name: str, o0: int, o1: int, b0: int = 0, b1: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Planar unpack of a rectangular Q40 sub-range: file rows
        [o0, o1) (the out axis) x 32-element blocks [b0, b1) of each row.

        Copies only the covered bytes out of the memmap — the unit of the
        STREAMING loader (models/loader), which pulls exactly one device
        shard's bytes at a time instead of materializing whole layer
        stacks on host (the TPU-native analogue of the reference's
        slice-by-slice socket streaming, src/llm.cpp:614-669). 2-D
        tensors only. Returns (q int8 [o1-o0, (b1-b0)*32],
        d f16 [o1-o0, b1-b0])."""
        from .quants import Q40_BLOCK_BYTES

        s = self.by_name[name]
        if s.float_type != FloatType.Q40 or len(s.shape) != 2:
            raise ValueError(f"{name}: ranged read needs a 2-D Q40 tensor")
        out, inner = s.shape
        nb = inner // 32
        if b1 is None:
            b1 = nb
        if not (0 <= o0 <= o1 <= out and 0 <= b0 <= b1 <= nb):
            raise ValueError(
                f"{name}: range rows [{o0},{o1}) blocks [{b0},{b1}) "
                f"outside ({out}, {nb})"
            )
        raw = self.raw(name).reshape(out, nb, Q40_BLOCK_BYTES)
        sub = np.ascontiguousarray(raw[o0:o1, b0:b1])
        q, d = q40_to_planar(sub.reshape(-1), (o1 - o0) * (b1 - b0) * 32)
        return q.reshape(o1 - o0, (b1 - b0) * 32), d.reshape(o1 - o0, b1 - b0)

    def planar_q40(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        """Tensor as planar int8 values [out, in] + f16 scales [out, in//32].

        This is the device layout for the Pallas quantized matmul path.
        """
        s = self.by_name[name]
        if s.float_type != FloatType.Q40:
            raise ValueError(f"{name} is {s.float_type}, not Q40")
        q, d = q40_to_planar(self.raw(name), s.n_elements)
        out, inner = s.shape[-2], s.shape[-1]
        lead = s.shape[:-2]
        return (
            q.reshape(*lead, out, inner),
            d.reshape(*lead, out, inner // 32),
        )

    def __iter__(self) -> Iterator[TensorSpec]:
        return iter(self.specs)
