"""`.m` model file writer.

Byte-compatible with the reference converter (converter/writer.py:92-148):
header is int32 KV pairs after (magic, headerSize); tensors follow in the
fixed plan order, each stored flat row-major in the requested float type.
"""

from __future__ import annotations

import struct
from typing import BinaryIO

import numpy as np

from .quants import FloatType, quantize_q40, quantize_q80

# key name -> int key, mirroring converter/writer.py:110-133
HEADER_KEYS = {
    "version": 0,
    "arch_type": 1,
    "dim": 2,
    "hidden_dim": 3,
    "n_layers": 4,
    "n_heads": 5,
    "n_kv_heads": 6,
    "n_experts": 7,
    "n_active_experts": 8,
    "vocab_size": 9,
    "max_seq_len": 10,
    "hidden_act": 11,
    "rope_theta": 12,
    "weights_float_type": 13,
    "rope_scaling_factor": 14,
    "rope_scaling_low_freq_factor": 15,
    "rope_scaling_high_freq_factory": 16,
    "rope_scaling_orig_max_seq_len": 17,
    "rope_type": 18,
    "head_dim": 19,
    "norm_epsilon": 20,
    "moe_hidden_dim": 21,
}


def write_header(f: BinaryIO, params: dict[str, int]) -> None:
    """Write the `.m` header (reference: converter/writer.py:109-148)."""
    data = b""
    for key, value in params.items():
        if key not in HEADER_KEYS:
            raise ValueError(f"unknown header key: {key}")
        data += struct.pack("<ii", HEADER_KEYS[key], int(value))
    f.write(struct.pack("<ii", 0x0A00ABCD, 8 + len(data)))
    f.write(data)


def write_tensor(f: BinaryIO, tensor: np.ndarray, float_type: FloatType) -> int:
    """Write one tensor flat row-major; returns bytes written."""
    flat = np.ascontiguousarray(tensor, dtype=np.float32).reshape(-1)
    ft = FloatType(float_type)
    if ft == FloatType.F32:
        raw = flat.tobytes()
    elif ft == FloatType.F16:
        raw = flat.astype(np.float16).tobytes()
    elif ft == FloatType.Q40:
        raw = quantize_q40(flat).tobytes()
    elif ft == FloatType.Q80:
        raw = quantize_q80(flat).tobytes()
    else:
        raise ValueError(f"unsupported float type: {ft}")
    f.write(raw)
    return len(raw)
