"""Q40 / Q80 block quantization formats.

Wire-compatible with distributed-llama's `.m` tensors
(reference: src/nn/nn-quants.hpp:53-72, converter/writer.py:29-74):

* **Q40** — 32-element blocks; per block an fp16 scale ``d`` followed by 16
  bytes of packed nibbles. Nibble ``j`` low half holds element ``j``, high
  half holds element ``j + 16``; dequantized value is ``(nibble - 8) * d``
  (reference: src/nn/nn-quants.cpp:229-246).
* **Q80** — 32-element blocks; fp16 scale ``d`` followed by 32 int8 values;
  value is ``q * d``.

Quantization rounding matches converter/writer.py exactly (asymmetric
``x/d + 8.5`` then clip to [0,15] for Q40; ``round(x/d)`` for Q80) so that
tensors we write are byte-identical with the reference converter's output.

These host-side codecs are numpy-vectorized. On device the framework never
touches this packed layout: weights are unpacked once at load time into a
planar (int8 values, fp scales) pair — `q40_to_planar` — which is the layout
the Pallas matmul kernel and the jnp dequant path both consume (int8 lanes
tile cleanly onto the TPU MXU/VPU; interleaved nibble+scale blocks do not).
"""

from __future__ import annotations

import enum

import numpy as np

Q40_BLOCK_SIZE = 32
Q80_BLOCK_SIZE = 32

Q40_BLOCK_BYTES = 2 + Q40_BLOCK_SIZE // 2  # fp16 scale + 16 packed bytes
Q80_BLOCK_BYTES = 2 + Q80_BLOCK_SIZE  # fp16 scale + 32 int8


class FloatType(enum.IntEnum):
    """Tensor storage types (reference: src/nn/nn-quants.hpp:56-62)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


_FLOAT_TYPE_NAMES = {
    FloatType.F32: "f32",
    FloatType.F16: "f16",
    FloatType.Q40: "q40",
    FloatType.Q80: "q80",
}


def parse_float_type(name: str) -> FloatType:
    for ft, n in _FLOAT_TYPE_NAMES.items():
        if n == name:
            return ft
    raise ValueError(f"unsupported float type: {name!r}")


def float_type_name(ft: FloatType) -> str:
    return _FLOAT_TYPE_NAMES[FloatType(ft)]


def tensor_bytes(ft: FloatType, n_elements: int) -> int:
    """Bytes of an n-element tensor stored as `ft` (reference: nn-core.cpp size math)."""
    ft = FloatType(ft)
    if ft == FloatType.F32:
        return 4 * n_elements
    if ft == FloatType.F16:
        return 2 * n_elements
    if ft == FloatType.Q40:
        assert n_elements % Q40_BLOCK_SIZE == 0
        return (n_elements // Q40_BLOCK_SIZE) * Q40_BLOCK_BYTES
    if ft == FloatType.Q80:
        assert n_elements % Q80_BLOCK_SIZE == 0
        return (n_elements // Q80_BLOCK_SIZE) * Q80_BLOCK_BYTES
    raise ValueError(f"unsupported float type: {ft}")


def _q40_scales(groups: np.ndarray) -> np.ndarray:
    """Per-block scale = extremum / -8, as in converter/writer.py:35-38."""
    gmax = groups.max(axis=1)
    gmin = groups.min(axis=1)
    return np.where(-gmin > gmax, gmin, gmax) / -8.0


def _safe_inverse(deltas: np.ndarray) -> np.ndarray:
    """1/deltas with 0 -> 0 (all-zero blocks, e.g. padded vocab rows)."""
    return np.divide(
        1.0, deltas, out=np.zeros_like(deltas), where=deltas != 0
    )


def quantize_q40(x: np.ndarray) -> np.ndarray:
    """Quantize a flat f32 array to packed Q40 bytes (uint8 array).

    Byte-identical with converter/writer.py:29-53.
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if x.size % Q40_BLOCK_SIZE != 0:
        raise ValueError(f"Q40 tensor size {x.size} not a multiple of {Q40_BLOCK_SIZE}")
    groups = x.reshape(-1, Q40_BLOCK_SIZE)
    deltas = _q40_scales(groups)
    deltas16 = deltas.astype(np.float16)
    inv = _safe_inverse(deltas)
    q = np.clip(groups * inv[:, None] + 8.5, 0, 15).astype(np.int64)
    half = Q40_BLOCK_SIZE // 2
    packed = (q[:, :half] & 0xF) | ((q[:, half:] & 0xF) << 4)

    out = np.empty((len(groups), Q40_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = packed.astype(np.uint8)
    return out.reshape(-1)


def quantize_q80(x: np.ndarray) -> np.ndarray:
    """Quantize a flat f32 array to packed Q80 bytes (uint8 array).

    Byte-identical with converter/writer.py:55-74.
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if x.size % Q80_BLOCK_SIZE != 0:
        raise ValueError(f"Q80 tensor size {x.size} not a multiple of {Q80_BLOCK_SIZE}")
    groups = x.reshape(-1, Q80_BLOCK_SIZE)
    gmax = groups.max(axis=1)
    gmin = groups.min(axis=1)
    absmax = np.where(-gmin > gmax, -gmin, gmax)
    deltas = absmax / 127.0
    deltas16 = deltas.astype(np.float16)
    inv = _safe_inverse(deltas)
    q = np.round(groups * inv[:, None]).astype(np.int8)

    out = np.empty((len(groups), Q80_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out.reshape(-1)


def q40_to_planar(raw: np.ndarray, n_elements: int) -> tuple[np.ndarray, np.ndarray]:
    """Unpack packed Q40 bytes into planar (values int8 in [-8,7], scales f16).

    Returns ``(q, d)`` with ``q.shape == (n_elements,)`` and
    ``d.shape == (n_elements // 32,)`` such that
    ``dequant[i] = q[i] * d[i // 32]``.
    """
    n_blocks = n_elements // Q40_BLOCK_SIZE
    raw = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * Q40_BLOCK_BYTES).reshape(
        n_blocks, Q40_BLOCK_BYTES
    )
    d = raw[:, :2].copy().view(np.float16).reshape(-1)
    packed = raw[:, 2:]
    half = Q40_BLOCK_SIZE // 2
    q = np.empty((n_blocks, Q40_BLOCK_SIZE), dtype=np.int8)
    q[:, :half] = (packed & 0xF).astype(np.int8) - 8
    q[:, half:] = (packed >> 4).astype(np.int8) - 8
    return q.reshape(-1), d


def pack_q40_device(
    q: np.ndarray, d: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Re-pack device-layout planar Q40 into the packed-nibble device
    format (weight_format="q40i4"; ops.quant_matmul.PackedQuantWeight).

    ``q`` int8 [..., in, out] values in [-8, 7], ``d`` float [..., in//32,
    out] scales -> (``qp`` int8 [..., in//2, out], ``d`` f16). The nibble
    pairing matches the wire format's own intra-block layout (byte j: low
    nibble element j, high nibble element j + 16), so the in-kernel unpack
    is the same shift/mask as `q40_to_planar`. Scales go back to f16 — the
    wire scale dtype, so the cast is exact and the device cost is
    0.5 + 2/32 = 0.5625 B/weight including scales."""
    *lead, inner, out = q.shape
    if inner % Q40_BLOCK_SIZE:
        raise ValueError(f"in dim {inner} not a multiple of {Q40_BLOCK_SIZE}")
    half = Q40_BLOCK_SIZE // 2
    blk = q.reshape(*lead, inner // Q40_BLOCK_SIZE, Q40_BLOCK_SIZE, out)
    lo = (blk[..., :half, :].astype(np.int16) + 8)
    hi = (blk[..., half:, :].astype(np.int16) + 8)
    qp = np.ascontiguousarray((lo | (hi << 4)).astype(np.uint8)).view(np.int8)
    return qp.reshape(*lead, inner // 2, out), d.astype(np.float16)


def q80_to_planar(raw: np.ndarray, n_elements: int) -> tuple[np.ndarray, np.ndarray]:
    """Unpack packed Q80 bytes into planar (values int8, scales f16)."""
    n_blocks = n_elements // Q80_BLOCK_SIZE
    raw = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * Q80_BLOCK_BYTES).reshape(
        n_blocks, Q80_BLOCK_BYTES
    )
    d = raw[:, :2].copy().view(np.float16).reshape(-1)
    q = raw[:, 2:].copy().view(np.int8)
    return q.reshape(-1), d


def dequantize_q40(raw: np.ndarray, n_elements: int, dtype=np.float32) -> np.ndarray:
    """Dequantize packed Q40 bytes to floats (reference: nn-quants.cpp:229-246)."""
    q, d = q40_to_planar(raw, n_elements)
    return (
        q.reshape(-1, Q40_BLOCK_SIZE).astype(np.float32) * d.astype(np.float32)[:, None]
    ).reshape(-1).astype(dtype)


def dequantize_q80(raw: np.ndarray, n_elements: int, dtype=np.float32) -> np.ndarray:
    """Dequantize packed Q80 bytes to floats (reference: nn-quants.cpp:180-191)."""
    q, d = q80_to_planar(raw, n_elements)
    return (
        q.reshape(-1, Q80_BLOCK_SIZE).astype(np.float32) * d.astype(np.float32)[:, None]
    ).reshape(-1).astype(dtype)


def quantize_q80_values(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize to planar Q80 (values, scales) without packing — numeric twin of
    the activation quantization the device performs in-kernel."""
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    groups = x.reshape(-1, Q80_BLOCK_SIZE)
    absmax = np.abs(groups).max(axis=1)
    deltas = (absmax / 127.0).astype(np.float16)
    inv = _safe_inverse(deltas.astype(np.float32))
    q = np.round(groups * inv[:, None]).astype(np.int8)
    return q.reshape(-1), deltas
