"""Reader/writer for distributed-llama's `.t` tokenizer file format.

Format (reference: src/tokenizer.cpp:42-164, converter/tokenizer-writer.py):

    int32 magic = 0x567124
    int32 headerSize                       # bytes, counting magic+headerSize
    int32 key, int32 value                 # repeated (TokenizerHeaderKey)
    bytes chatTemplate[CHAT_TEMPLATE]      # if key present (value = length)
    int32 eosTokenId * N_EOS_TOKENS
    per token: float32 score, int32 length, bytes token[length]

Notes mirrored from the reference:
  * ``CHAT_STOP`` payloads are skipped (src/tokenizer.cpp:87);
  * ``EOS_ID`` / ``CHAT_EOS_ID`` keys append to the EOS set (back-compat);
  * the vocab splits into regular tokens [0, bos_id) and special tokens
    [bos_id, vocab_size) — the same "unstable assumption" the reference
    makes (src/tokenizer.cpp:138-140).
"""

from __future__ import annotations

import dataclasses
import enum
import struct

TOKENIZER_MAGIC = 0x567124
TOKENIZER_OLD_MAGIC = 0x567123


class TokHeaderKey(enum.IntEnum):
    """`.t` header keys (reference: src/tokenizer.hpp:21-33)."""

    VERSION = 0
    VOCAB_SIZE = 1
    MAX_TOKEN_LENGTH = 2
    BOS_ID = 3
    EOS_ID = 4  # backward compatibility
    PAD_ID = 5  # ignored
    CHAT_EOS_ID = 6  # backward compatibility
    CHAT_TEMPLATE = 7
    CHAT_STOP = 8  # ignored (payload skipped)
    N_EOS_TOKENS = 9
    ADD_BOS = 10


@dataclasses.dataclass
class TokenizerData:
    """Raw contents of a `.t` file."""

    vocab: list[bytes]
    scores: list[float]
    bos_id: int
    add_bos: bool
    eos_token_ids: list[int]
    chat_template: str | None
    max_token_length: int

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)


def _read_vocab(f, vocab_size: int) -> tuple[list[bytes], list[float]]:
    """Per-token (score, length, bytes) section shared by both header formats
    (reference: src/tokenizer.cpp:125-136)."""
    vocab: list[bytes] = []
    scores: list[float] = []
    for _ in range(vocab_size):
        score, length = struct.unpack("<fi", f.read(8))
        if length < 1:
            raise ValueError(f"invalid token length: {length}")
        vocab.append(f.read(length))
        scores.append(score)
    return vocab, scores


def read_tokenizer(path: str) -> TokenizerData:
    """Parse a `.t` file (reference: src/tokenizer.cpp:42-164)."""
    with open(path, "rb") as f:
        (magic,) = struct.unpack("<i", f.read(4))
        if magic == TOKENIZER_OLD_MAGIC:
            return _read_old_tokenizer(f)
        if magic != TOKENIZER_MAGIC:
            raise ValueError(f"invalid tokenizer magic: {magic:#x}")

        (header_size,) = struct.unpack("<i", f.read(4))
        n_kv_ints = (header_size - 8) // 4
        kv = struct.unpack(f"<{n_kv_ints}i", f.read(n_kv_ints * 4))

        version = -1
        vocab_size = 0
        max_token_length = 0
        bos_id = -1
        add_bos = False
        chat_template_length = -1
        n_eos_tokens = 0
        eos_token_ids: list[int] = []
        skip_bytes = 0
        for key, value in zip(kv[0::2], kv[1::2]):
            key = TokHeaderKey(key)
            if key == TokHeaderKey.VERSION:
                version = value
            elif key == TokHeaderKey.VOCAB_SIZE:
                vocab_size = value
            elif key == TokHeaderKey.MAX_TOKEN_LENGTH:
                max_token_length = value
            elif key == TokHeaderKey.BOS_ID:
                bos_id = value
            elif key in (TokHeaderKey.EOS_ID, TokHeaderKey.CHAT_EOS_ID):
                eos_token_ids.append(value)
            elif key == TokHeaderKey.CHAT_TEMPLATE:
                chat_template_length = value
            elif key == TokHeaderKey.CHAT_STOP:
                skip_bytes += value
            elif key == TokHeaderKey.PAD_ID:
                pass
            elif key == TokHeaderKey.N_EOS_TOKENS:
                n_eos_tokens = value
            elif key == TokHeaderKey.ADD_BOS:
                add_bos = value == 1

        if version != 1:
            raise ValueError("old tokenizer version, please regenerate your tokenizer")
        if skip_bytes:
            f.seek(skip_bytes, 1)

        chat_template: str | None = None
        if chat_template_length > 0:
            chat_template = f.read(chat_template_length).decode("utf-8")
        for _ in range(n_eos_tokens):
            (eos_id,) = struct.unpack("<i", f.read(4))
            eos_token_ids.append(eos_id)

        if max_token_length < 1:
            raise ValueError("invalid tokenizer max token length")

        vocab, scores = _read_vocab(f, vocab_size)

    return TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        add_bos=add_bos,
        eos_token_ids=eos_token_ids,
        chat_template=chat_template,
        max_token_length=max_token_length,
    )


def _read_old_tokenizer(f) -> TokenizerData:
    """Read the legacy fixed-header format (magic 0x567123): the 5-field
    TokenizerOldHeader then the vocab section (reference:
    src/tokenizer.hpp:13-19, src/tokenizer.cpp:57-64)."""
    vocab_size, max_token_length, bos_id, eos_id, _pad_id = struct.unpack(
        "<IIiii", f.read(20)
    )
    if max_token_length < 1:
        raise ValueError("invalid tokenizer max token length")
    vocab, scores = _read_vocab(f, vocab_size)
    return TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        # The old header carries no add_bos flag (the reference leaves the
        # field unset on this path); legacy sentencepiece tokenizers prepend
        # BOS, so default True.
        add_bos=True,
        eos_token_ids=[eos_id],
        chat_template=None,
        max_token_length=max_token_length,
    )


def write_tokenizer(path: str, data: TokenizerData) -> None:
    """Write a `.t` file byte-compatible with converter/tokenizer-writer.py."""
    params: list[tuple[TokHeaderKey, int]] = [
        (TokHeaderKey.BOS_ID, data.bos_id),
        (TokHeaderKey.VERSION, 1),
        (TokHeaderKey.VOCAB_SIZE, len(data.vocab)),
        (TokHeaderKey.MAX_TOKEN_LENGTH, max(len(t) for t in data.vocab)),
    ]
    template_bytes = (
        data.chat_template.encode("utf-8") if data.chat_template is not None else None
    )
    if template_bytes:
        params.append((TokHeaderKey.CHAT_TEMPLATE, len(template_bytes)))
    params.append((TokHeaderKey.N_EOS_TOKENS, len(data.eos_token_ids)))
    params.append((TokHeaderKey.ADD_BOS, 1 if data.add_bos else 0))

    kv_data = b"".join(struct.pack("<ii", int(k), v) for k, v in params)
    with open(path, "wb") as f:
        f.write(struct.pack("<ii", TOKENIZER_MAGIC, 8 + len(kv_data)))
        f.write(kv_data)
        if template_bytes:
            f.write(template_bytes)
        for eos_id in data.eos_token_ids:
            f.write(struct.pack("<i", eos_id))
        for token, score in zip(data.vocab, data.scores):
            assert len(token) > 0
            f.write(struct.pack("<fI", score, len(token)))
            f.write(token)
