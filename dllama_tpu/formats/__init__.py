from .quants import (
    Q40_BLOCK_SIZE,
    Q80_BLOCK_SIZE,
    FloatType,
    quantize_q40,
    quantize_q80,
    dequantize_q40,
    dequantize_q80,
    q40_to_planar,
    q80_to_planar,
    tensor_bytes,
)
from .model_file import LlmArch, LlmHeader, RopeType, read_llm_header, ModelReader
from .tokenizer_file import TokenizerData, read_tokenizer, write_tokenizer

__all__ = [
    "Q40_BLOCK_SIZE",
    "Q80_BLOCK_SIZE",
    "FloatType",
    "quantize_q40",
    "quantize_q80",
    "dequantize_q40",
    "dequantize_q80",
    "q40_to_planar",
    "q80_to_planar",
    "tensor_bytes",
    "LlmArch",
    "LlmHeader",
    "RopeType",
    "read_llm_header",
    "ModelReader",
    "TokenizerData",
    "read_tokenizer",
    "write_tokenizer",
]
