"""Load `.m` weights into the transformer's params pytree.

TPU-native counterpart of the reference's weight loading + distribution
(loadLlmNetWeight, src/llm.cpp:614-669): the reference root slices every
matmul weight per node and ships slices over TCP; here each tensor is read
(streamed via memmap), transposed to the [in, out] matmul layout, stacked
across layers for `lax.scan`, and `jax.device_put` with a NamedSharding does
the slicing — XLA/ICI plays the role of the socket loader.

Llama q/k row permutation note: the converter pre-permutes q/k rows to the
interleaved-rope layout (converter/convert-hf.py:13-16), so like the
reference we consume the file as-is and use interleaved RoPE for llama.
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.model_file import LlmArch, LlmHeader, ModelReader
from ..formats.quants import FloatType, pack_q40_device
from ..ops.jnp_ops import rope_cache
from ..ops.quant_matmul import (
    FusedQuantWeight,
    PackedQuantWeight,
    QuantWeight,
    planar_to_device_layout,
)
from ..utils import native
from .transformer import Params

# Placement hook: receives (name, np array) and returns the device array.
# The TP engine passes a function that applies the right NamedSharding;
# default is plain device_put semantics via jnp.asarray.
PutFn = Callable[[str, np.ndarray], jnp.ndarray]


def _default_put(name: str, arr: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(arr)


def _interleave_concat(arrs: list[np.ndarray], tp: int) -> np.ndarray:
    """Concatenate matmul weights along the out (last) axis in SHARD-MAJOR
    order: [a_0 | b_0 | ... | a_1 | b_1 | ...] where x_i is tensor x's i-th
    of `tp` out-dim slices. Under the row-split PartitionSpec (.., "tp")
    each tp shard then holds its own slice of EVERY constituent, so one
    fused kernel launch computes what separate launches did, and the
    outputs un-interleave with local reshapes (transformer._split_fused) —
    no cross-shard data movement."""
    for a in arrs:
        if a.shape[-1] % tp != 0:
            raise ValueError(
                f"fused out dim {a.shape[-1]} not divisible by tp={tp}"
            )
    if tp == 1:
        return np.concatenate(arrs, axis=-1)
    chunks = []
    for s in range(tp):
        for a in arrs:
            o = a.shape[-1] // tp
            chunks.append(a[..., s * o : (s + 1) * o])
    return np.concatenate(chunks, axis=-1)


def _lead_indices(lead_sls, lead_shape):
    """Cartesian product of the lead-axis slice ranges (layer / expert)."""
    import itertools

    ranges = [
        range(*sl.indices(n)) for sl, n in zip(lead_sls, lead_shape)
    ]
    return list(itertools.product(*ranges)) if ranges else [()]


def _stream_quant_stack(
    reader: ModelReader,
    put: PutFn,
    tag: str,
    name_fns: list,
    lead_shape: tuple[int, ...],
    fuse: int = 1,
    packed: bool = False,
):
    """Stacked QuantWeight built WITHOUT materializing the host stack.

    Iterates the sharding's device->index map and answers each shard
    with ranged reads off the memmap (native C++ unpack;
    ModelReader.planar_q40_range as the pure-numpy fallback), one unpack
    per DISTINCT shard index (replicas reuse it), so the host high-water
    mark is one shard plus one row-range — not the full [L(, E), in, out] stack
    (at Llama-70B the w13 stack alone is ~37 GB of host RAM; the
    reference streams node slices over sockets for the same reason,
    src/llm.cpp:614-669).

    `name_fns`: one per-(lead idx) tensor-name fn, or several for a
    FUSED weight — constituents interleave shard-major in `fuse` chunks
    (the _interleave_concat layout restated as index math, so a fused
    shard never touches the other shards' bytes).

    `packed` re-packs each shard into the nibble device format
    (weight_format="q40i4": int8 byte = two int4 values, f16 scales)
    HOST-SIDE before device_put — the device never sees the 1 B/value
    layout, and the fused qkv/w13 interleave metadata is unchanged
    because packing acts on the in axis while the interleave permutes
    the out axis.

    Returns (QuantWeight | PackedQuantWeight, out_dims) with out_dims the
    constituents' global out dims (FusedQuantWeight metadata)."""
    from ..formats.quants import Q40_BLOCK_BYTES

    sh = getattr(put, "sharding")(tag)
    zero = tuple(0 for _ in lead_shape)
    specs0 = [reader.by_name[fn(*zero)] for fn in name_fns]
    inner = specs0[0].shape[1]
    douts = [s.shape[0] for s in specs0]
    for s in specs0:
        if s.shape[1] != inner:
            raise ValueError(f"{tag}: fused constituents disagree on in dim")
    total_out = sum(douts)
    nb = inner // 32
    widths = [d // fuse for d in douts]
    for d in douts:
        if d % fuse:
            raise ValueError(f"fused out dim {d} not divisible by tp={fuse}")
    cw = sum(widths)
    offs = [0]
    for w_ in widths[:-1]:
        offs.append(offs[-1] + w_)

    def fused_parts(g0: int, g1: int):
        """(constituent j, file rows [c0, c1)) pieces covering the fused
        out range [g0, g1), in fused order."""
        g = g0
        while g < g1:
            s, r = divmod(g, cw)
            j = 0
            while r >= offs[j] + widths[j]:
                j += 1
            take = min(g1 - g, offs[j] + widths[j] - r)
            c0 = s * widths[j] + (r - offs[j])
            yield j, c0, c0 + take
            g += take

    def ranged_both(lead_idx, g0, g1, b0, b1):
        """Device-layout (values [i, o] int8, scales [i//32, o] f32) for
        one lead index; ONE unpack pass feeds both leaves (native C++
        when built). Full-width rows slice the memmap zero-copy; block
        sub-ranges copy exactly the shard's bytes first."""
        qs, ds = [], []
        for j, c0, c1 in fused_parts(g0, g1):
            name = name_fns[j](*lead_idx)
            sub_inner = (b1 - b0) * 32
            if b0 == 0 and b1 == nb:
                rowb = nb * Q40_BLOCK_BYTES
                raw = reader.raw(name)[c0 * rowb : c1 * rowb]
            else:
                full = reader.raw(name).reshape(-1, nb, Q40_BLOCK_BYTES)
                raw = np.ascontiguousarray(full[c0:c1, b0:b1]).reshape(-1)
            unpacked = native.q40_unpack_transposed(raw, c1 - c0, sub_inner)
            if unpacked is None:
                q, d = reader.planar_q40_range(name, c0, c1, b0, b1)
                unpacked = (
                    np.ascontiguousarray(q.T),
                    np.ascontiguousarray(d.T).astype(np.float32),
                )
            qs.append(unpacked[0])
            ds.append(unpacked[1])
        if len(qs) == 1:
            return qs[0], ds[0]
        return np.concatenate(qs, axis=1), np.concatenate(ds, axis=1)

    q_shape = (*lead_shape, inner, total_out)
    d_shape = (*lead_shape, nb, total_out)
    q_map = sh.addressable_devices_indices_map(q_shape)
    d_map = sh.addressable_devices_indices_map(d_shape)
    # group devices by DISTINCT shard index (dp replicas share one
    # unpack), then build -> device_put -> FREE one shard at a time: the
    # host never holds more than one shard's numpy buffers (holding all
    # of them was a ~2x-largest-tensor transient, enough to OOM the
    # 125 GB rehearsal host at 70B scale)
    by_key: dict = {}
    for dev, q_idx in q_map.items():
        key = tuple((sl.start, sl.stop, sl.step) for sl in q_idx)
        by_key.setdefault(key, (q_idx, []))[1].append(dev)
    q_parts: dict = {}
    d_parts: dict = {}
    for key, (q_idx, devs) in by_key.items():
        *lead_sls, i_sl, o_sl = q_idx
        i0, i1, _ = i_sl.indices(inner)
        o0, o1, _ = o_sl.indices(total_out)
        if i0 % 32 or i1 % 32:
            raise ValueError(f"{tag}: shard slice [{i0},{i1}) not 32-aligned")
        b0, b1 = i0 // 32, i1 // 32
        db_sl = d_map[devs[0]][len(lead_sls)]
        if db_sl.indices(nb)[:2] != (b0, b1):  # leaves must shard alike
            raise ValueError(f"{tag}: value/scale shard maps disagree")
        leads = _lead_indices(lead_sls, lead_shape)
        lead_lens = [
            len(range(*sl.indices(n))) for sl, n in zip(lead_sls, lead_shape)
        ]
        # preallocate at the final shard shape and write each lead index's
        # unpack (and optional nibble re-pack) in place: a pairs list +
        # np.stack would hold TWO copies of the shard at once — several GB
        # of transient for a 70B w13 tp shard
        sub_inner = (b1 - b0) * 32
        q_np = np.empty(
            (len(leads), sub_inner // 2 if packed else sub_inner, o1 - o0),
            np.int8,
        )
        d_np = np.empty(
            (len(leads), b1 - b0, o1 - o0),
            np.float16 if packed else np.float32,
        )
        for i, li in enumerate(leads):
            q_i, d_i = ranged_both(li, o0, o1, b0, b1)
            if packed:
                q_i, d_i = pack_q40_device(q_i, d_i)
            q_np[i] = q_i
            d_np[i] = d_i
            del q_i, d_i
        q_np = q_np.reshape(*lead_lens, *q_np.shape[1:])
        d_np = d_np.reshape(*lead_lens, *d_np.shape[1:])
        for dev in devs:
            q_parts[dev] = jax.device_put(q_np, dev)
            d_parts[dev] = jax.device_put(d_np, dev)
        jax.block_until_ready(  # transfers done before freeing the source
            [q_parts[d] for d in devs] + [d_parts[d] for d in devs]
        )
        del q_np, d_np
    out_q_shape = (*lead_shape, inner // 2, total_out) if packed else q_shape
    q_arr = jax.make_array_from_single_device_arrays(
        out_q_shape, sh, [q_parts[d] for d in q_map]
    )
    d_arr = jax.make_array_from_single_device_arrays(
        d_shape, getattr(put, "sharding")(tag), [d_parts[d] for d in q_map]
    )
    cls = PackedQuantWeight if packed else QuantWeight
    return cls(q_arr, d_arr), tuple(douts)


def load_params(
    reader: ModelReader,
    dtype=jnp.float32,
    put: PutFn = _default_put,
    weight_format: str = "dense",
    fuse: int = 0,
) -> Params:
    """Materialize the params pytree from a `.m` file.

    `dtype` is the activation/matmul dtype for the dense (dequantized)
    path — f32 for exactness tests, bf16 for TPU speed. Norm weights and
    the rope cache stay f32.

    `weight_format="q40"` keeps the matmul weights block-quantized on
    device as `QuantWeight` (int8 values + f32 scales, the Pallas kernel's
    layout) instead of dequantizing — ~3.6x less HBM traffic per decode
    step. Requires a Q40 file. MoE expert weights are kept quantized too
    (the ragged kernel dequantizes selected blocks in VMEM), so a Q40 MoE
    file's device footprint stays ~1.125 B/weight instead of blowing up to
    bf16 density.

    `weight_format="q40i4"` additionally re-packs the matmul weights into
    the nibble device format (`PackedQuantWeight`: two int4 values per
    byte + f16 scales, 0.5625 B/weight) host-side during the load; the
    Pallas kernel unpacks in VMEM after the HBM copy. MoE expert weights
    stay int8 `QuantWeight` (the ragged MoE kernels consume that layout),
    same policy as q40i8's requantize.

    `fuse` (quantized path only): the tp shard count; > 0 emits fused
    "wqkv" (q|k|v) and, for dense-FFN archs, "w13" (w1|w3) weights in
    shard-major interleaved layout instead of the separate tensors —
    decode drops from 7 to 4 Pallas launches per layer and reads the
    activations once per pair (the round-3 silicon probe measured ~41 us
    fixed cost per kernel launch; scripts/kernel_sweep.py). Must equal the
    mesh's tp axis size.
    """
    h = reader.header
    quantize = weight_format in ("q40", "q40i4")
    packed = weight_format == "q40i4"
    if quantize and h.weight_type != FloatType.Q40:
        raise ValueError(
            f"weight_format={weight_format!r} needs a Q40 model file, got "
            f"{h.weight_type.name}"
        )
    # Streamed shard-by-shard placement whenever the put hook exposes its
    # shardings (shard_params_put does); DLLAMA_STREAM_LOAD=0 forces the
    # host-stack path (kept for single-device puts and as the oracle the
    # streamed path is tested against).
    streaming = (
        quantize
        and getattr(put, "sharding", None) is not None
        and os.environ.get("DLLAMA_STREAM_LOAD", "1") != "0"
    )

    def w(name: str, transpose: bool = True) -> np.ndarray:
        spec = reader.by_name[name]
        if (
            transpose
            and spec.float_type == FloatType.Q40
            and len(spec.shape) == 2
        ):
            # multithreaded C++ dequant straight into the transposed layout
            out_dim, in_dim = spec.shape
            a = native.q40_dequant_transposed(reader.raw(name), out_dim, in_dim)
            if a is not None:
                return a
        a = reader.dense_f32(name)
        if transpose:
            if a.ndim == 2 and a.size >= 1 << 20:
                t = native.f32_transpose(a)
                if t is not None:
                    return t
            a = np.ascontiguousarray(a.T)  # file is (out, in) -> we want (in, out)
        return a

    def stack(fn: Callable[[int], np.ndarray]) -> np.ndarray:
        return np.stack([fn(l) for l in range(h.n_layers)])

    def unpack_q40(name: str) -> tuple[np.ndarray, np.ndarray]:
        """Q40 tensor -> (q int8 [in, out], d f32 [in//32, out]) device
        layout; native C++ unpack when built (one multithreaded pass),
        numpy fallback otherwise."""
        out_dim, in_dim = reader.by_name[name].shape
        unpacked = native.q40_unpack_transposed(reader.raw(name), out_dim, in_dim)
        if unpacked is None:
            unpacked = planar_to_device_layout(*reader.planar_q40(name))
        return unpacked

    def qw(tag: str, fn: Callable[[int], str]):
        """Stacked QuantWeight (or PackedQuantWeight when packed) for a
        per-layer matmul tensor."""
        if streaming:
            w_, _ = _stream_quant_stack(
                reader, put, tag, [fn], (h.n_layers,), packed=packed
            )
            return w_
        qs, ds = [], []
        for l in range(h.n_layers):
            q_arr, d_arr = unpack_q40(fn(l))
            if packed:
                q_arr, d_arr = pack_q40_device(q_arr, d_arr)
            qs.append(q_arr)
            ds.append(d_arr)
        cls = PackedQuantWeight if packed else QuantWeight
        return cls(put(tag, np.stack(qs)), put(tag, np.stack(ds)))

    layers: dict[str, jnp.ndarray] = {}
    layers["att_norm"] = put(
        "att_norm", stack(lambda l: w(f"layers.{l}.att_norm", False))
    )
    layers["ffn_norm"] = put(
        "ffn_norm", stack(lambda l: w(f"layers.{l}.ffn_norm", False))
    )
    def qw_fused(tag: str, names: list[Callable[[int], str]]) -> FusedQuantWeight:
        """Stacked FusedQuantWeight fusing several row-split matmul tensors
        along the out axis, shard-major for `fuse` tp shards; the fuse
        factor and constituent out dims ride as static pytree metadata."""
        if streaming:
            w_, dims = _stream_quant_stack(
                reader, put, tag, names, (h.n_layers,), fuse=fuse,
                packed=packed,
            )
            return FusedQuantWeight(w_, fuse, dims)
        qs, ds = [], []
        dims: tuple[int, ...] = ()
        for l in range(h.n_layers):
            parts = [unpack_q40(fn(l)) for fn in names]
            dims = tuple(p[0].shape[-1] for p in parts)
            # interleave permutes the out axis, packing halves the in
            # axis — they commute, so the fuse/dims metadata is the same
            # for both device formats
            q_l = _interleave_concat([p[0] for p in parts], fuse)
            d_l = _interleave_concat([p[1] for p in parts], fuse)
            if packed:
                q_l, d_l = pack_q40_device(q_l, d_l)
            qs.append(q_l)
            ds.append(d_l)
        cls = PackedQuantWeight if packed else QuantWeight
        return FusedQuantWeight(
            cls(put(tag, np.stack(qs)), put(tag, np.stack(ds))),
            fuse,
            dims,
        )

    if quantize and fuse:
        layers["wqkv"] = qw_fused(
            "wqkv",
            [
                lambda l: f"layers.{l}.q",
                lambda l: f"layers.{l}.k",
                lambda l: f"layers.{l}.v",
            ],
        )
        layers["wo"] = qw("wo", lambda l: f"layers.{l}.wo")
    elif quantize:
        layers["wq"] = qw("wq", lambda l: f"layers.{l}.q")
        layers["wk"] = qw("wk", lambda l: f"layers.{l}.k")
        layers["wv"] = qw("wv", lambda l: f"layers.{l}.v")
        layers["wo"] = qw("wo", lambda l: f"layers.{l}.wo")
    else:
        layers["wq"] = put("wq", stack(lambda l: w(f"layers.{l}.q")).astype(dtype))
        layers["wk"] = put("wk", stack(lambda l: w(f"layers.{l}.k")).astype(dtype))
        layers["wv"] = put("wv", stack(lambda l: w(f"layers.{l}.v")).astype(dtype))
        layers["wo"] = put("wo", stack(lambda l: w(f"layers.{l}.wo")).astype(dtype))

    if h.arch == LlmArch.QWEN3_MOE:
        layers["moe_gate"] = put(
            "moe_gate", stack(lambda l: w(f"layers.{l}.moe_gate"))
        )

        if quantize:
            # Experts stay block-quantized on device (the reference stores
            # and ships experts Q40 too: src/llm.cpp:425-499,
            # src/nn/nn-network.cpp:856-888); the ragged MoE kernel
            # dequantizes selected blocks in VMEM. Layout per expert is the
            # same [in, out] device layout as the dense matmuls, stacked
            # [L, E, ...]. Under weight_format="q40i4" the experts KEEP
            # this int8 layout (the ragged MoE kernels consume it; same
            # policy as q40i8's requantize, int8_matmul.requantize_params).
            def qexperts(tag: str, which: str) -> QuantWeight:
                if streaming:
                    w_, _ = _stream_quant_stack(
                        reader, put, tag,
                        [lambda l, e, wh=which: f"layers.{l}.experts.{e}.{wh}"],
                        (h.n_layers, h.n_experts),
                    )
                    return w_
                lqs, lds = [], []
                for l in range(h.n_layers):
                    unpacked = [
                        unpack_q40(f"layers.{l}.experts.{e}.{which}")
                        for e in range(h.n_experts)
                    ]
                    lqs.append(np.stack([u[0] for u in unpacked]))
                    lds.append(np.stack([u[1] for u in unpacked]))
                return QuantWeight(put(tag, np.stack(lqs)), put(tag, np.stack(lds)))

            layers["w1"] = qexperts("w1", "w1")
            layers["w2"] = qexperts("w2", "w2")
            layers["w3"] = qexperts("w3", "w3")
        else:

            def experts(l: int, which: str) -> np.ndarray:
                return np.stack(
                    [w(f"layers.{l}.experts.{e}.{which}") for e in range(h.n_experts)]
                )

            layers["w1"] = put("w1", stack(lambda l: experts(l, "w1")).astype(dtype))
            layers["w2"] = put("w2", stack(lambda l: experts(l, "w2")).astype(dtype))
            layers["w3"] = put("w3", stack(lambda l: experts(l, "w3")).astype(dtype))
    elif quantize and fuse:
        layers["w13"] = qw_fused(
            "w13",
            [lambda l: f"layers.{l}.w1", lambda l: f"layers.{l}.w3"],
        )
        layers["w2"] = qw("w2", lambda l: f"layers.{l}.w2")
    elif quantize:
        layers["w1"] = qw("w1", lambda l: f"layers.{l}.w1")
        layers["w2"] = qw("w2", lambda l: f"layers.{l}.w2")
        layers["w3"] = qw("w3", lambda l: f"layers.{l}.w3")
    else:
        layers["w1"] = put("w1", stack(lambda l: w(f"layers.{l}.w1")).astype(dtype))
        layers["w2"] = put("w2", stack(lambda l: w(f"layers.{l}.w2")).astype(dtype))
        layers["w3"] = put("w3", stack(lambda l: w(f"layers.{l}.w3")).astype(dtype))

    if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        layers["q_norm"] = put(
            "q_norm", stack(lambda l: w(f"layers.{l}.q_norm", False))
        )
        layers["k_norm"] = put(
            "k_norm", stack(lambda l: w(f"layers.{l}.k_norm", False))
        )

    cos, sin = rope_cache(h)
    if quantize and streaming:
        wcls, _ = _stream_quant_stack(
            reader, put, "wcls", [lambda: "wcls"], (), packed=packed
        )
    elif quantize:
        q_arr, d_arr = unpack_q40("wcls")
        if packed:
            q_arr, d_arr = pack_q40_device(q_arr, d_arr)
        cls = PackedQuantWeight if packed else QuantWeight
        wcls = cls(put("wcls", q_arr), put("wcls", d_arr))
    else:
        wcls = put("wcls", w("wcls").astype(dtype))
    params: Params = {
        "embed": put("embed", reader.dense_f32("embed").astype(dtype)),
        "wcls": wcls,
        "final_norm": put("final_norm", w("final_norm", False)),
        "rope_cos": put("rope_cos", np.asarray(cos)),
        "rope_sin": put("rope_sin", np.asarray(sin)),
        "layers": layers,
    }
    return params
