"""Load `.m` weights into the transformer's params pytree.

TPU-native counterpart of the reference's weight loading + distribution
(loadLlmNetWeight, src/llm.cpp:614-669): the reference root slices every
matmul weight per node and ships slices over TCP; here each tensor is read
(streamed via memmap), transposed to the [in, out] matmul layout, stacked
across layers for `lax.scan`, and `jax.device_put` with a NamedSharding does
the slicing — XLA/ICI plays the role of the socket loader.

Llama q/k row permutation note: the converter pre-permutes q/k rows to the
interleaved-rope layout (converter/convert-hf.py:13-16), so like the
reference we consume the file as-is and use interleaved RoPE for llama.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..formats.model_file import LlmArch, LlmHeader, ModelReader
from ..formats.quants import FloatType
from ..ops.jnp_ops import rope_cache
from ..ops.quant_matmul import (
    FusedQuantWeight,
    QuantWeight,
    planar_to_device_layout,
)
from ..utils import native
from .transformer import Params

# Placement hook: receives (name, np array) and returns the device array.
# The TP engine passes a function that applies the right NamedSharding;
# default is plain device_put semantics via jnp.asarray.
PutFn = Callable[[str, np.ndarray], jnp.ndarray]


def _default_put(name: str, arr: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(arr)


def _interleave_concat(arrs: list[np.ndarray], tp: int) -> np.ndarray:
    """Concatenate matmul weights along the out (last) axis in SHARD-MAJOR
    order: [a_0 | b_0 | ... | a_1 | b_1 | ...] where x_i is tensor x's i-th
    of `tp` out-dim slices. Under the row-split PartitionSpec (.., "tp")
    each tp shard then holds its own slice of EVERY constituent, so one
    fused kernel launch computes what separate launches did, and the
    outputs un-interleave with local reshapes (transformer._split_fused) —
    no cross-shard data movement."""
    for a in arrs:
        if a.shape[-1] % tp != 0:
            raise ValueError(
                f"fused out dim {a.shape[-1]} not divisible by tp={tp}"
            )
    if tp == 1:
        return np.concatenate(arrs, axis=-1)
    chunks = []
    for s in range(tp):
        for a in arrs:
            o = a.shape[-1] // tp
            chunks.append(a[..., s * o : (s + 1) * o])
    return np.concatenate(chunks, axis=-1)


def load_params(
    reader: ModelReader,
    dtype=jnp.float32,
    put: PutFn = _default_put,
    weight_format: str = "dense",
    fuse: int = 0,
) -> Params:
    """Materialize the params pytree from a `.m` file.

    `dtype` is the activation/matmul dtype for the dense (dequantized)
    path — f32 for exactness tests, bf16 for TPU speed. Norm weights and
    the rope cache stay f32.

    `weight_format="q40"` keeps the matmul weights block-quantized on
    device as `QuantWeight` (int8 values + f32 scales, the Pallas kernel's
    layout) instead of dequantizing — ~3.6x less HBM traffic per decode
    step. Requires a Q40 file. MoE expert weights are kept quantized too
    (the ragged kernel dequantizes selected blocks in VMEM), so a Q40 MoE
    file's device footprint stays ~1.125 B/weight instead of blowing up to
    bf16 density.

    `fuse` (quantized path only): the tp shard count; > 0 emits fused
    "wqkv" (q|k|v) and, for dense-FFN archs, "w13" (w1|w3) weights in
    shard-major interleaved layout instead of the separate tensors —
    decode drops from 7 to 4 Pallas launches per layer and reads the
    activations once per pair (the round-3 silicon probe measured ~41 us
    fixed cost per kernel launch; scripts/kernel_sweep.py). Must equal the
    mesh's tp axis size.
    """
    h = reader.header
    quantize = weight_format == "q40"
    if quantize and h.weight_type != FloatType.Q40:
        raise ValueError(
            f"weight_format='q40' needs a Q40 model file, got "
            f"{h.weight_type.name}"
        )

    def w(name: str, transpose: bool = True) -> np.ndarray:
        spec = reader.by_name[name]
        if (
            transpose
            and spec.float_type == FloatType.Q40
            and len(spec.shape) == 2
        ):
            # multithreaded C++ dequant straight into the transposed layout
            out_dim, in_dim = spec.shape
            a = native.q40_dequant_transposed(reader.raw(name), out_dim, in_dim)
            if a is not None:
                return a
        a = reader.dense_f32(name)
        if transpose:
            if a.ndim == 2 and a.size >= 1 << 20:
                t = native.f32_transpose(a)
                if t is not None:
                    return t
            a = np.ascontiguousarray(a.T)  # file is (out, in) -> we want (in, out)
        return a

    def stack(fn: Callable[[int], np.ndarray]) -> np.ndarray:
        return np.stack([fn(l) for l in range(h.n_layers)])

    def unpack_q40(name: str) -> tuple[np.ndarray, np.ndarray]:
        """Q40 tensor -> (q int8 [in, out], d f32 [in//32, out]) device
        layout; native C++ unpack when built (one multithreaded pass),
        numpy fallback otherwise."""
        out_dim, in_dim = reader.by_name[name].shape
        unpacked = native.q40_unpack_transposed(reader.raw(name), out_dim, in_dim)
        if unpacked is None:
            unpacked = planar_to_device_layout(*reader.planar_q40(name))
        return unpacked

    def qw(tag: str, fn: Callable[[int], str]):
        """Stacked QuantWeight for a per-layer matmul tensor."""
        qs, ds = [], []
        for l in range(h.n_layers):
            q_arr, d_arr = unpack_q40(fn(l))
            qs.append(q_arr)
            ds.append(d_arr)
        return QuantWeight(put(tag, np.stack(qs)), put(tag, np.stack(ds)))

    layers: dict[str, jnp.ndarray] = {}
    layers["att_norm"] = put(
        "att_norm", stack(lambda l: w(f"layers.{l}.att_norm", False))
    )
    layers["ffn_norm"] = put(
        "ffn_norm", stack(lambda l: w(f"layers.{l}.ffn_norm", False))
    )
    def qw_fused(tag: str, names: list[Callable[[int], str]]) -> FusedQuantWeight:
        """Stacked FusedQuantWeight fusing several row-split matmul tensors
        along the out axis, shard-major for `fuse` tp shards; the fuse
        factor and constituent out dims ride as static pytree metadata."""
        qs, ds = [], []
        dims: tuple[int, ...] = ()
        for l in range(h.n_layers):
            parts = [unpack_q40(fn(l)) for fn in names]
            dims = tuple(p[0].shape[-1] for p in parts)
            qs.append(_interleave_concat([p[0] for p in parts], fuse))
            ds.append(_interleave_concat([p[1] for p in parts], fuse))
        return FusedQuantWeight(
            QuantWeight(put(tag, np.stack(qs)), put(tag, np.stack(ds))),
            fuse,
            dims,
        )

    if quantize and fuse:
        layers["wqkv"] = qw_fused(
            "wqkv",
            [
                lambda l: f"layers.{l}.q",
                lambda l: f"layers.{l}.k",
                lambda l: f"layers.{l}.v",
            ],
        )
        layers["wo"] = qw("wo", lambda l: f"layers.{l}.wo")
    elif quantize:
        layers["wq"] = qw("wq", lambda l: f"layers.{l}.q")
        layers["wk"] = qw("wk", lambda l: f"layers.{l}.k")
        layers["wv"] = qw("wv", lambda l: f"layers.{l}.v")
        layers["wo"] = qw("wo", lambda l: f"layers.{l}.wo")
    else:
        layers["wq"] = put("wq", stack(lambda l: w(f"layers.{l}.q")).astype(dtype))
        layers["wk"] = put("wk", stack(lambda l: w(f"layers.{l}.k")).astype(dtype))
        layers["wv"] = put("wv", stack(lambda l: w(f"layers.{l}.v")).astype(dtype))
        layers["wo"] = put("wo", stack(lambda l: w(f"layers.{l}.wo")).astype(dtype))

    if h.arch == LlmArch.QWEN3_MOE:
        layers["moe_gate"] = put(
            "moe_gate", stack(lambda l: w(f"layers.{l}.moe_gate"))
        )

        if quantize:
            # Experts stay block-quantized on device (the reference stores
            # and ships experts Q40 too: src/llm.cpp:425-499,
            # src/nn/nn-network.cpp:856-888); the ragged MoE kernel
            # dequantizes selected blocks in VMEM. Layout per expert is the
            # same [in, out] device layout as the dense matmuls, stacked
            # [L, E, ...].
            def qexperts(tag: str, which: str) -> QuantWeight:
                lqs, lds = [], []
                for l in range(h.n_layers):
                    unpacked = [
                        unpack_q40(f"layers.{l}.experts.{e}.{which}")
                        for e in range(h.n_experts)
                    ]
                    lqs.append(np.stack([u[0] for u in unpacked]))
                    lds.append(np.stack([u[1] for u in unpacked]))
                return QuantWeight(put(tag, np.stack(lqs)), put(tag, np.stack(lds)))

            layers["w1"] = qexperts("w1", "w1")
            layers["w2"] = qexperts("w2", "w2")
            layers["w3"] = qexperts("w3", "w3")
        else:

            def experts(l: int, which: str) -> np.ndarray:
                return np.stack(
                    [w(f"layers.{l}.experts.{e}.{which}") for e in range(h.n_experts)]
                )

            layers["w1"] = put("w1", stack(lambda l: experts(l, "w1")).astype(dtype))
            layers["w2"] = put("w2", stack(lambda l: experts(l, "w2")).astype(dtype))
            layers["w3"] = put("w3", stack(lambda l: experts(l, "w3")).astype(dtype))
    elif quantize and fuse:
        layers["w13"] = qw_fused(
            "w13",
            [lambda l: f"layers.{l}.w1", lambda l: f"layers.{l}.w3"],
        )
        layers["w2"] = qw("w2", lambda l: f"layers.{l}.w2")
    elif quantize:
        layers["w1"] = qw("w1", lambda l: f"layers.{l}.w1")
        layers["w2"] = qw("w2", lambda l: f"layers.{l}.w2")
        layers["w3"] = qw("w3", lambda l: f"layers.{l}.w3")
    else:
        layers["w1"] = put("w1", stack(lambda l: w(f"layers.{l}.w1")).astype(dtype))
        layers["w2"] = put("w2", stack(lambda l: w(f"layers.{l}.w2")).astype(dtype))
        layers["w3"] = put("w3", stack(lambda l: w(f"layers.{l}.w3")).astype(dtype))

    if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        layers["q_norm"] = put(
            "q_norm", stack(lambda l: w(f"layers.{l}.q_norm", False))
        )
        layers["k_norm"] = put(
            "k_norm", stack(lambda l: w(f"layers.{l}.k_norm", False))
        )

    cos, sin = rope_cache(h)
    if quantize:
        q_arr, d_arr = unpack_q40("wcls")
        wcls = QuantWeight(put("wcls", q_arr), put("wcls", d_arr))
    else:
        wcls = put("wcls", w("wcls").astype(dtype))
    params: Params = {
        "embed": put("embed", reader.dense_f32("embed").astype(dtype)),
        "wcls": wcls,
        "final_norm": put("final_norm", w("final_norm", False)),
        "rope_cos": put("rope_cos", np.asarray(cos)),
        "rope_sin": put("rope_sin", np.asarray(sin)),
        "layers": layers,
    }
    return params
