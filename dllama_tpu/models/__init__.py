from .transformer import forward, init_kv_cache, Params, KvCache
from .loader import load_params

__all__ = ["forward", "init_kv_cache", "load_params", "Params", "KvCache"]
