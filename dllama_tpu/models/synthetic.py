"""Synthetic model construction: headers + random params without a `.m` file.

Used by bench.py, __graft_entry__.py and tests to exercise the full model
path at arbitrary scale without multi-GB downloads. Shapes and pytree layout
are identical to models/loader.load_params output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.model_file import HiddenAct, LlmArch, LlmHeader, RopeType
from ..formats.quants import FloatType
from ..ops.jnp_ops import rope_cache
from .transformer import Params

# Real-model shape presets (from the reference's supported model zoo,
# launch.py:17-73 / BASELINE.json configs).
PRESETS = {
    "llama-1b": dict(
        dim=2048, hidden_dim=8192, n_layers=16, n_heads=32, n_kv_heads=8,
        head_dim=64, vocab_size=128256, seq_len=131072, rope_theta=500000.0,
    ),
    "llama-8b": dict(
        dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
        head_dim=128, vocab_size=128256, seq_len=131072, rope_theta=500000.0,
    ),
    "llama-70b": dict(
        dim=8192, hidden_dim=28672, n_layers=80, n_heads=64, n_kv_heads=8,
        head_dim=128, vocab_size=128256, seq_len=131072, rope_theta=500000.0,
    ),
    "qwen3-14b": dict(
        dim=5120, hidden_dim=17408, n_layers=40, n_heads=40, n_kv_heads=8,
        head_dim=128, vocab_size=151936, seq_len=40960, rope_theta=1000000.0,
        arch=LlmArch.QWEN3,
    ),
    "qwen3-30b-a3b": dict(
        dim=2048, hidden_dim=6144, moe_hidden_dim=768, n_layers=48,
        n_heads=32, n_kv_heads=4, head_dim=128, vocab_size=151936,
        seq_len=40960, rope_theta=1000000.0, arch=LlmArch.QWEN3_MOE,
        n_experts=128, n_active_experts=8,
    ),
    "tiny": dict(
        dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, vocab_size=256, seq_len=64,
    ),
}


def make_header(preset: str | dict, max_seq_len: int = 0) -> LlmHeader:
    cfg = dict(PRESETS[preset]) if isinstance(preset, str) else dict(preset)
    h = LlmHeader()
    h.arch = cfg.pop("arch", LlmArch.LLAMA)
    h.n_experts = cfg.pop("n_experts", 0)
    h.n_active_experts = cfg.pop("n_active_experts", 0)
    h.moe_hidden_dim = cfg.pop("moe_hidden_dim", 0)
    h.rope_theta = cfg.pop("rope_theta", 10000.0)
    for k, v in cfg.items():
        setattr(h, k, v)
    h.orig_seq_len = h.seq_len
    if max_seq_len and h.seq_len > max_seq_len:
        h.seq_len = max_seq_len
    if h.head_dim == 0:
        h.head_dim = h.dim // h.n_heads
    h.hidden_act = HiddenAct.SILU
    h.weight_type = FloatType.Q40
    h.rope_type = (
        RopeType.FALCON
        if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE)
        else RopeType.LLAMA
    )
    h.norm_epsilon = 1e-5
    return h


def write_synth_model(
    path,
    preset: str | dict = "llama-70b",
    seed: int = 0,
    max_seq_len: int = 4096,
    n_layers: int | None = None,
    tile_bytes: int = 8 << 20,
):
    """Stream a synthetic random Q40 `.m` of ARBITRARY size to disk with
    O(tile) host memory: every Q40 tensor is a tiling of one pre-packed
    random row per distinct width, norms are 1.0, f32 tensors tile a
    random row. Content quality is irrelevant for what this feeds — fit
    and loader-streaming rehearsals at real checkpoint scale
    (docs/70b_plan.md); numeric parity oracles use real converter files.
    Returns the LlmHeader describing the file."""
    from ..formats.model_file import tensor_plan
    from ..formats.quants import quantize_q40
    from ..formats.writer import write_header

    cfg = dict(PRESETS[preset]) if isinstance(preset, str) else dict(preset)
    if n_layers is not None:
        cfg["n_layers"] = n_layers
    h = make_header(cfg, max_seq_len=max_seq_len)
    params = {
        "version": 0,
        "arch_type": int(h.arch),
        "dim": h.dim,
        "hidden_dim": h.hidden_dim,
        "n_layers": h.n_layers,
        "n_heads": h.n_heads,
        "n_kv_heads": h.n_kv_heads,
        "n_experts": h.n_experts,
        "n_active_experts": h.n_active_experts,
        "vocab_size": h.vocab_size,
        "max_seq_len": h.seq_len,
        "hidden_act": int(h.hidden_act),
        "rope_theta": int(h.rope_theta),
        "weights_float_type": int(FloatType.Q40),
        "head_dim": h.head_dim,
        "norm_epsilon": 5,  # header quirk: eps rides as an enum (5 = 1e-5)
    }
    if h.arch == LlmArch.QWEN3_MOE:
        params["moe_hidden_dim"] = h.moe_hidden_dim
    rng = np.random.default_rng(seed)
    packed_rows: dict[int, bytes] = {}

    def q40_row(inner: int) -> bytes:
        if inner not in packed_rows:
            packed_rows[inner] = quantize_q40(
                (rng.standard_normal(inner) * 0.02).astype(np.float32)
            ).tobytes()
        return packed_rows[inner]

    with open(path, "wb") as f:
        write_header(f, params)
        for spec in tensor_plan(h):
            if spec.float_type == FloatType.F32:
                if "norm" in spec.name:
                    f.write(np.ones(spec.shape, np.float32).tobytes())
                    continue
                inner = spec.shape[-1]
                n_rows = int(np.prod(spec.shape[:-1], dtype=np.int64))
                row = (rng.standard_normal(inner) * 0.02).astype(np.float32)
                buf = row.tobytes()
                reps = max(1, tile_bytes // len(buf))
                tile = buf * reps
                full, rem = divmod(n_rows, reps)
                for _ in range(full):
                    f.write(tile)
                if rem:
                    f.write(buf * rem)
            elif spec.float_type == FloatType.Q40:
                out, inner = spec.shape[-2], spec.shape[-1]
                out *= int(np.prod(spec.shape[:-2], dtype=np.int64))
                row = q40_row(inner)
                reps = max(1, tile_bytes // len(row))
                tile = row * reps
                full, rem = divmod(out, reps)
                for _ in range(full):
                    f.write(tile)
                if rem:
                    f.write(row * rem)
            else:  # pragma: no cover - synth files are Q40+F32 only
                raise ValueError(f"unsupported synth type {spec.float_type}")
    return h


def random_params(
    h: LlmHeader,
    dtype=jnp.bfloat16,
    seed: int = 0,
    mesh=None,
    put=None,  # kept for API symmetry with load_params; unused when mesh given
    weight_format: str = "dense",
    fuse: int = 0,
) -> Params:
    """Random params pytree with the loader's exact layout, generated
    directly ON DEVICE (jit + out_shardings): no multi-GB host->device
    transfer, which matters when the chip sits behind a slow tunnel.

    Pass `mesh` to get TP-sharded parameters (same rules as
    parallel.sharding.param_spec_tree)."""
    from jax.sharding import NamedSharding, PartitionSpec

    specs = None
    if mesh is not None:
        from ..parallel.sharding import param_spec_tree

        specs = param_spec_tree(h)

    root_key = jax.random.PRNGKey(seed)
    scale = 0.02

    def sharding_for(name):
        if specs is None:
            return None
        spec = specs.get(name)
        if spec is None:
            spec = specs["layers"].get(name, PartitionSpec())
        return NamedSharding(mesh, spec)

    def mk(name, *shape, norm=False):
        sh = sharding_for(name)
        if norm:
            f = jax.jit(
                lambda: jnp.ones(shape, jnp.float32), out_shardings=sh
            )
            return f()
        import zlib

        key = jax.random.fold_in(root_key, zlib.crc32(name.encode()))
        f = jax.jit(
            lambda k: jax.random.normal(k, shape, dtype) * jnp.asarray(scale, dtype),
            out_shardings=sh,
        )
        return f(key)

    def mk_quant(name, *shape, packed=False):
        """Random QuantWeight [..., in, out] on device: int8 values in
        [-8, 7] + f32 per-block scales (the loader's q40 layout). With
        `packed` the q40i4 device layout instead: nibble-packed int8
        [..., in//2, out] + f16 scales — any byte is a valid nibble pair,
        so the packed tensor is generated directly at its final shape."""
        import zlib

        from ..ops.quant_matmul import PackedQuantWeight, QuantWeight

        sh = sharding_for(name)
        *lead, inner, out = shape
        key = jax.random.fold_in(root_key, zlib.crc32(name.encode()))
        kq, kd = jax.random.split(key)
        q_shape = (*lead, inner // 2, out) if packed else shape
        q = jax.jit(
            lambda k: (
                jax.random.randint(k, q_shape, -128, 128, dtype=jnp.int8)
                if packed
                else jax.random.randint(k, q_shape, -8, 8, dtype=jnp.int8)
            ),
            out_shardings=sh,
        )(kq)
        d_shape = (*lead, inner // 32, out)
        d_dtype = jnp.float16 if packed else jnp.float32
        d = jax.jit(
            lambda k: jax.random.uniform(
                k, d_shape, jnp.float32, minval=0.5 * scale / 8, maxval=scale / 8
            ).astype(d_dtype),
            out_shardings=sh,
        )(kd)
        cls = PackedQuantWeight if packed else QuantWeight
        return cls(q, d)

    def dev(name, arr):
        sh = sharding_for(name)
        arr = jnp.asarray(arr)
        return jax.device_put(arr, sh) if sh is not None else arr

    L, D, HD = h.n_layers, h.dim, h.head_dim
    QD, KD, FF, V = h.q_dim, h.kv_dim, h.ff_dim, h.vocab_size
    moe = h.arch == LlmArch.QWEN3_MOE
    E = h.n_experts

    quant = weight_format in ("q40", "q40i8", "q40i4")
    packed = weight_format == "q40i4"
    if quant:
        def mm(name, *shape, expert=False):
            # MoE experts stay int8 QuantWeight under q40i4 (the ragged
            # kernels consume that layout; loader policy)
            return mk_quant(name, *shape, packed=packed and not expert)
    else:
        def mm(name, *shape, expert=False):
            return mk(name, *shape)
    layers = {
        "att_norm": mk("att_norm", L, D, norm=True),
        "ffn_norm": mk("ffn_norm", L, D, norm=True),
        "wo": mm("wo", L, QD, D),
        # MoE experts follow the loader's policy: quantized on device for
        # q40 (the ragged/grouped kernels dequantize selected blocks in
        # VMEM), dense otherwise
        "w1": mm("w1", L, E, D, FF, expert=True) if moe else mm("w1", L, D, FF),
        "w2": mm("w2", L, E, FF, D, expert=True) if moe else mm("w2", L, FF, D),
        "w3": mm("w3", L, E, D, FF, expert=True) if moe else mm("w3", L, D, FF),
    }
    if quant and fuse:
        # fused-launch layout (loader `fuse`): the content is random either
        # way, so generate the fused tensors directly in their shapes
        from ..ops.quant_matmul import FusedQuantWeight

        layers["wqkv"] = FusedQuantWeight(
            mm("wqkv", L, D, QD + 2 * KD), fuse, (QD, KD, KD)
        )
        if not moe:
            del layers["w1"], layers["w3"]
            layers["w13"] = FusedQuantWeight(
                mm("w13", L, D, 2 * FF), fuse, (FF, FF)
            )
    else:
        layers["wq"] = mm("wq", L, D, QD)
        layers["wk"] = mm("wk", L, D, KD)
        layers["wv"] = mm("wv", L, D, KD)
    if moe:
        gate_key = jax.random.fold_in(root_key, 12345)
        layers["moe_gate"] = jax.jit(
            lambda k: jax.random.normal(k, (L, D, E), jnp.float32) * scale,
            out_shardings=sharding_for("moe_gate"),
        )(gate_key)
    if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        layers["q_norm"] = mk("q_norm", L, HD, norm=True)
        layers["k_norm"] = mk("k_norm", L, HD, norm=True)

    cos, sin = rope_cache(h)
    params = {
        "embed": mk("embed", V, D),
        "wcls": mm("wcls", D, V),
        "final_norm": mk("final_norm", D, norm=True),
        "rope_cos": dev("rope_cos", cos),
        "rope_sin": dev("rope_sin", sin),
        "layers": layers,
    }
    if weight_format == "q40i8":
        # same load path as the engine: build q40, requantize on device
        from ..ops.int8_matmul import pick_group, requantize_params

        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        params = requantize_params(params, h, pick_group(h, tp))
    return params
