"""Pure-functional decoder forward pass for Llama / Qwen3 / Qwen3-MoE.

This is the TPU-native re-design of the reference's graph builder
(src/llm.cpp:151-605): where the reference emits an explicit per-node op
graph (segments, pipes, sync steps) interpreted by a pthread executor, here
the model is a single jit-traced function — XLA fuses what the reference
scheduled by hand, and the reference's cross-node sync points (its
SYNC_NODE_SLICES all-gather + OP_MERGE_ADD reduce = an all-reduce of the
row/col-split matmul partial sums) become sharding constraints that make XLA
insert `all-reduce` collectives over ICI (see parallel/sharding.py).

Layer walk per token (reference: src/llm.cpp:263-557):
    x += attn(rms_norm(x))     # q/k/v proj, [qk-norm,] rope, kv-cache, GQA attention, wo
    x += ffn(rms_norm(x))      # swiglu w1/w3 -> w2, or MoE gate/topk/experts
    logits = rms_norm(x) @ wcls

Shapes: tokens [B, T] -> logits [B, T, V]. The reference is B=1 with T the
prefill chunk (its `nBatches`); we keep a real batch axis as a data-parallel
surface. The KV cache is [L, B, nKvHeads, S, headDim] (HEAD-MAJOR) — the
kv-head axis is the tensor-parallel shard axis, mirroring the reference's
KV split (sliceKvCache, src/nn/nn-core.cpp:211-218), and per-head (S, hd)
planes are what the Pallas flash kernels tile (Mosaic's last-two-dims rule
rejects blocking a size-1 head dim; see ops/flash_attention.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..formats.model_file import HiddenAct, LlmArch, LlmHeader, RopeType
from ..ops.jnp_ops import apply_rope, gelu, qk_rms_norm, rms_norm, silu
from ..ops.int8_matmul import Int8Weight, i8matmul_tp
from ..ops.quant_matmul import (
    PackedQuantWeight,
    QuantWeight,
    dequant,
    qmatmul_tp,
)

# both Q40 device formats ride the same qmatmul dispatch (the packed
# variant unpacks nibbles in VMEM); MoE expert leaves stay plain
# QuantWeight under every quantized format, so the expert kernels below
# test for that class alone
_QUANT_CLASSES = (QuantWeight, PackedQuantWeight)
from ..ops.flash_attention import flash_attention, pick_flash_blocks
# QuantKV lives in ops/kv_cache so the flash kernels consume it natively
# (no models<->ops cycle); re-exported here for engine/cli/pipeline use.
from ..ops.kv_cache import (
    QuantKV,
    dequant_kv,
    quantize_kv_rows,
    slice_kv as _slice_kv,
)
from ..ops.moe_kernel import (
    moe_active_experts,
    moe_active_experts_q40,
    moe_grouped_experts,
    moe_grouped_experts_q40,
)

Params = Dict[str, Any]
KvCache = Dict[str, jnp.ndarray]

_NEG_INF = -1e30


def _int8_flash_enabled() -> bool:
    """int8-KV-native flash prefill (default on). DLLAMA_INT8_FLASH=0 is
    the operational escape hatch restoring the r4 dequant-then-kernel
    path — the [bs, 1] scale-ref BlockSpec is interpret-validated but
    first compiles on real Mosaic via scripts/tpu_validation.py's
    'flash QuantKV' checks."""
    import os

    return os.environ.get("DLLAMA_INT8_FLASH", "1") != "0"


def _mm(x: jnp.ndarray, w, role: str, mesh, sync_quant: bool = False) -> jnp.ndarray:
    """Matmul dispatch: dense [in, out] weights take the einsum path (GSPMD
    partitions them via the NamedSharding specs); Q40 QuantWeight leaves take
    the Pallas kernel (shard_map'd per TP role on a mesh). `sync_quant`
    Q80-compresses the col-split partial-sum all-reduce payload
    (reference: --buffer-float-type q80)."""
    if isinstance(w, Int8Weight):
        return i8matmul_tp(x, w, role, mesh, sync_quant=sync_quant).astype(x.dtype)
    if isinstance(w, _QUANT_CLASSES):
        return qmatmul_tp(x, w, role, mesh, sync_quant=sync_quant).astype(x.dtype)
    return jnp.einsum("bti,io->bto", x, w)


def _mm_manual(
    x: jnp.ndarray, w, role: str, axis: str | None, sync_quant: bool = False
) -> jnp.ndarray:
    """Matmul for MANUAL-collective contexts (inside an enclosing
    shard_map, e.g. a pipeline stage's tp group): `w` is already this
    shard's local slice, the kernel runs locally, and the col-split
    partial sum all-reduces over `axis` exactly where qmatmul_tp's own
    shard_map would have psummed — in f32, downcasting AFTER the
    reduction like the flat path (rounding each partial before summing
    would compound per layer). `sync_quant` Q80-compresses the psum
    payload (the reference's --buffer-float-type q80), same as the flat
    path. `axis=None` = single-shard stage."""
    from ..ops.quant_matmul import qmatmul

    def reduce(out):
        if role == "col" and axis is not None:
            from ..parallel.collectives import psum_maybe_quantized

            return psum_maybe_quantized(out, axis, sync_quant)
        return out

    if isinstance(w, Int8Weight):
        from ..ops.int8_matmul import i8matmul

        return reduce(i8matmul(x, w)).astype(x.dtype)
    if isinstance(w, _QUANT_CLASSES):
        return reduce(qmatmul(x, w)).astype(x.dtype)
    return reduce(jnp.einsum("bti,io->bto", x, w))


def _split_fused(out: jnp.ndarray, tp: int, dims: tuple[int, ...]):
    """Un-interleave a fused row-split matmul output [B, T, sum(dims)]
    whose columns are laid out shard-major (loader._interleave_concat):
    shard s's columns are [a_s | b_s | ...]. Returns one [B, T, dim]
    array per constituent with its global column order restored. All ops
    factor the tp-sharded axis into (tp, local) and slice the replicated
    local axis, so under GSPMD they stay shard-local."""
    b, t, total = out.shape
    locs = [d // tp for d in dims]
    assert sum(locs) * tp == total, (dims, tp, total)
    o = out.reshape(b, t, tp, sum(locs))
    parts, off = [], 0
    for dl, dg in zip(locs, dims):
        parts.append(o[..., off : off + dl].reshape(b, t, dg))
        off += dl
    return parts




def init_kv_cache(
    h: LlmHeader, batch_size: int, dtype=jnp.float32, seq_len: int | None = None
) -> KvCache:
    """Allocate the KV cache (reference allocates per-layer f32 k/v buffers,
    src/llm.cpp:260-261). dtype jnp.int8 allocates the quantized layout
    (QuantKV leaves)."""
    s = seq_len or h.seq_len
    shape = (h.n_layers, batch_size, h.n_kv_heads, s, h.head_dim)
    if dtype == jnp.int8:
        def leaf():
            return QuantKV(
                jnp.zeros(shape, jnp.int8),
                jnp.ones(shape[:-1] + (1,), jnp.float32),
            )

        return {"k": leaf(), "v": leaf()}
    return {
        "k": jnp.zeros(shape, dtype=dtype),
        "v": jnp.zeros(shape, dtype=dtype),
    }


def _attention_tp(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,  # [B, KH, S, hd]
    pos: jnp.ndarray,
    head_dim: int,
    mesh,
    attn_window: int = 0,  # sp only: global window, sliced per sp shard
) -> jnp.ndarray:
    """Attention dispatch on TPU: XLA dense attention for T=1 decode over
    the (window-sliced) cache, the prefill flash kernel for T >= 8
    (blockwise online softmax, no [T, S] score materialization — the
    long-context replacement for multiheadAtt_F32), einsum elsewhere.

    Decode deliberately does NOT use the Pallas flash-decode kernel: the
    round-3 silicon probe (scripts/decode_probe.py) showed (a) Mosaic does
    not elide the HBM->VMEM copy when a clamped BlockSpec index repeats,
    so the kernel reads the WHOLE cache every step regardless of pos, and
    (b) XLA's own dense T=1 attention is faster on the same cache
    (0.25 vs 0.40 ms/iter on a 33 MB cache). O(pos) decode reads come
    from the engine's bucketed attn_window slicing instead — the O(pos)
    property of the reference's decode attention
    (src/nn/nn-cpu-ops.cpp:753-788) lives in the window, not the kernel.

    Heads are the TP axis (reference: sliceMultiHeadAtt), so the kernels
    run per-shard under shard_map with no collectives.
    """
    b, t = q.shape[0], q.shape[1]
    per_lane = jnp.ndim(pos) == 1
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        # QuantKV rides into the sp shard_map quantized; the bodies
        # slice their local window first, then dequant — so int8 + sp
        # reads stay windowed AND int8-sized across the boundary
        return _attention_sp(
            q, k_cache, v_cache, pos, head_dim, mesh,
            attn_window=attn_window,
        )
    on_tpu = jax.default_backend() == "tpu"
    s = k_cache.shape[2]
    if on_tpu and t >= 8 and pick_flash_blocks(t, s) is not None:
        # QuantKV rides into the kernel natively (int8 planes + [bs, 1]
        # scale refs; dequant on the VMEM tile) — int8 prefill reads
        # ~half the HBM bytes of bf16 and never materializes a dense
        # cache copy (VERDICT r4 #3). DLLAMA_INT8_FLASH=0 restores the
        # dequant-then-kernel path (escape hatch until the scale-ref
        # BlockSpec has passed scripts/tpu_validation.py on silicon).
        if not _int8_flash_enabled():
            k_cache = dequant_kv(k_cache, q.dtype)
            v_cache = dequant_kv(v_cache, q.dtype)
        kernel = flash_attention  # handles scalar and per-lane pos
    else:
        k_cache = dequant_kv(k_cache, q.dtype)
        v_cache = dequant_kv(v_cache, q.dtype)
        return _attention(q, k_cache, v_cache, pos, head_dim)
    n_heads = q.shape[2]
    if mesh is None or mesh.devices.size == 1:
        out = kernel(q, k_cache, v_cache, pos)
    else:
        from ..utils.compat import shard_map_compat as shard_map
        from jax.sharding import PartitionSpec as P

        spec_q = P("dp", None, "tp", None)
        spec_kv = P("dp", "tp", None, None)
        pos_spec = P("dp") if per_lane else P()
        out = shard_map(
            lambda qq, kk, vv, pp: kernel(qq, kk, vv, pp),
            mesh=mesh,
            in_specs=(spec_q, spec_kv, spec_kv, pos_spec),
            out_specs=spec_q,
            check_vma=False,
        )(q, k_cache, v_cache, pos)
    return out.reshape(b, t, n_heads * head_dim)


def _attention_sp_merge(
    qq: jnp.ndarray,  # [B, T, H, hd] — full queries, replicated over sp
    kk: jnp.ndarray,  # [B, KH, S/sp, hd] — LOCAL sequence shard (cyclic)
    vv: jnp.ndarray,
    pos,  # scalar or [B] query positions (global coordinates)
    sp_axis: str,
    sp_n: int,
) -> jnp.ndarray:
    """Merged-stats sequence-parallel attention for callers ALREADY inside
    a shard_map: each sp shard computes online-softmax partial state over
    its local KV rows, merged with a log-sum-exp pmax/psum over `sp_axis`.
    Collective payload is [B, KH, G, T](+hd) — tiny next to the cache
    reads it splits. Used by the flat-mesh decode path (_attention_sp)
    and by run_layers' manual sp mode inside pipeline stages (sp_axis).

    The sequence layout is CYCLIC: shard i's local row j holds global
    position j*sp + i (strided key positions in the stats math). This is
    what makes attention windows tile the sp axis — the live prefix
    [0, pos] spreads evenly over shards, so a global window w (an sp*512
    multiple) is exactly the local prefix [0, w/sp) on every shard; with
    the contiguous block layout early shards are fully live and no
    uniform static local slice can shrink reads (engine._attn_window).
    Returns [B, T, H, hd]."""
    from ..ops.jnp_ops import attention_stats

    idx = lax.axis_index(sp_axis)
    acc, m, l = attention_stats(qq, kk, vv, pos, idx, s_stride=sp_n)
    m_g = lax.pmax(m, sp_axis)
    scale = jnp.where(m <= _NEG_INF / 2, 0.0, jnp.exp(m - m_g))
    l_g = lax.psum(l * scale, sp_axis)
    acc_g = lax.psum(acc * scale[..., None], sp_axis)
    l_safe = jnp.where(l_g == 0.0, 1.0, l_g)
    out = acc_g / l_safe[..., None]  # [b, kh, g, t, hd]
    bb, kh, g, tq, hd = out.shape
    return (
        out.transpose(0, 3, 1, 2, 4)
        .reshape(bb, tq, kh * g, hd)
        .astype(qq.dtype)
    )


def _attention_sp(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd] — S sharded over "sp", CYCLIC
    v_cache: jnp.ndarray,
    pos: jnp.ndarray,
    head_dim: int,
    mesh,
    attn_window: int = 0,
) -> jnp.ndarray:
    """Sequence-parallel attention: the KV cache's sequence axis lives on
    the `sp` mesh axis (the long-context scaling axis the reference lacks —
    SURVEY.md §5 marks SP/ring absent there), in the CYCLIC row order
    (global position g at shard g % sp, local row g // sp — see
    _attention_sp_merge for why this is the windowable layout).

    Decode (T=1): every sp shard computes online-softmax partial state over
    its local KV rows, merged with a log-sum-exp pmax/psum — the collective
    payload is [B, KH, G, 1(, hd)], tiny next to the cache reads it saves.
    `pos` may be a [B] per-lane vector (continuous batching composes with
    sp): the stats math broadcasts per-lane query positions, and a parked
    lane's strongly negative sentinel masks it on every shard.

    Prefill (T % sp == 0): queries shard over sp too and the KV shards
    rotate around the ring (parallel/ring_attention.ring_attention_local,
    cyclic mode), overlapping each hop's ppermute with the local compute.

    `attn_window` (a multiple of sp) slices every shard's LOCAL prefix to
    window/sp rows before attending — O(pos) decode reads on the
    long-context axis, the same engine-window mechanism the sp=1 path
    uses (VERDICT r3 item 5 closed).

    Heads stay tp-sharded inside the same shard_map — attention needs no
    tp collectives (reference: sliceMultiHeadAtt head independence)."""
    from ..utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import ring_attention_local

    b, t, n_heads = q.shape[0], q.shape[1], q.shape[2]
    s = k_cache.shape[2]
    sp = mesh.shape["sp"]
    shard = s // sp
    w_loc = 0
    if attn_window and attn_window < s:
        if attn_window % sp:
            raise ValueError(
                f"attn_window {attn_window} must be a multiple of sp={sp}"
            )
        w_loc = attn_window // sp
    kv_spec = P("dp", "tp", "sp", None)
    per_lane = jnp.ndim(pos) == 1
    pos_spec = P("dp") if per_lane else P()

    if t == 1:
        q_spec = P("dp", None, "tp", None)
        # dense jnp stats as the local step: the silicon probe
        # (scripts/decode_probe.py) showed XLA's dense T=1 attention beats
        # the Pallas decode kernel and that the kernel's pos-clamped DMA
        # schedule does not actually elide copies on Mosaic — so the
        # Pallas local step (flash_decode_stats) buys nothing here

        def body(qq, kk, vv, pp):
            kk = dequant_kv(_slice_kv(kk, w_loc), qq.dtype)
            vv = dequant_kv(_slice_kv(vv, w_loc), qq.dtype)
            return _attention_sp_merge(qq, kk, vv, pp, "sp", sp)

    else:
        q_spec = P("dp", "sp", "tp", None)
        # cyclic key layout: the flash-stats local step handles strided
        # key positions (ops/flash_attention s_stride), auto-selected on
        # TPU when the per-shard shapes tile. An int8 QuantKV shard rides
        # the ring QUANTIZED: the kernel dequants per-tile in VMEM, the
        # jnp fallback dequants locally, and each ppermute hop moves int8
        # payloads — halving both HBM reads and ICI traffic vs the r4
        # dense materialization (VERDICT r4 #3). Ring hops rotate only
        # the windowed local prefix, shrinking payloads with the window.
        tq_local = t // sp
        rows_local = w_loc or shard
        quant = isinstance(k_cache, QuantKV)
        int8_native = _int8_flash_enabled()
        use_flash = (
            jax.default_backend() == "tpu"
            and (int8_native or not quant)
            and pick_flash_blocks(tq_local, rows_local) is not None
        )

        def body(qq, kk, vv, pp):
            idx = lax.axis_index("sp")
            tq = qq.shape[1]
            kk = _slice_kv(kk, w_loc)
            vv = _slice_kv(vv, w_loc)
            if quant and not int8_native:
                # escape hatch (DLLAMA_INT8_FLASH=0): the r4 behavior —
                # local dense view, jnp ring step
                kk = dequant_kv(kk, qq.dtype)
                vv = dequant_kv(vv, qq.dtype)
            return ring_attention_local(
                qq, kk, vv,
                q_pos0=pp + idx * tq,
                shard_size=jnp.int32(shard),
                axis_name="sp",
                use_flash=use_flash,
                cyclic=True,
            )

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, pos_spec),
        out_specs=q_spec,
        check_vma=False,
    )(q, k_cache, v_cache, pos)
    return out.reshape(b, t, n_heads * head_dim)


def _attention(
    q: jnp.ndarray,  # [B, T, H, hd]
    k_cache: jnp.ndarray,  # [B, KH, S, hd]
    v_cache: jnp.ndarray,  # [B, KH, S, hd]
    pos: jnp.ndarray,  # scalar int32: absolute position of tokens[:, 0]
    head_dim: int,
) -> jnp.ndarray:
    """Causal GQA attention over the full cache, flattened to
    [B, T, H * hd]; math lives in ops/jnp_ops.attention_dense (reference:
    multiheadAtt_F32, src/nn/nn-cpu-ops.cpp:753-788)."""
    from ..ops.jnp_ops import attention_dense

    b, t, n_heads, _ = q.shape
    out = attention_dense(q, k_cache, v_cache, pos)
    return out.reshape(b, t, n_heads * head_dim)


def _moe_route(x_flat: jnp.ndarray, gate_w: jnp.ndarray, n_active: int):
    """Shared gate routing (softmax over all experts -> top-k -> normTopk=1
    weights; reference: src/nn/nn-cpu-ops.cpp:1462-1492). `x_flat` is
    [..., D]; returns (top_i [..., k], weights [..., k]) in f32."""
    logits = jnp.einsum(
        "...d,de->...e", x_flat.astype(jnp.float32), gate_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, n_active)
    weights = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_i, weights


def _moe_ffn(
    x: jnp.ndarray,  # [B, T, D]
    gate_w: jnp.ndarray,  # [D, E]
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    n_active: int,
    act,
) -> jnp.ndarray:
    """MoE FFN: softmax over all experts -> top-k -> normalized weights ->
    weighted sum of expert SwiGLU outputs.

    (reference: the OP_SOFTMAX / OP_MOE_GATE / 3x OP_MATMUL / OP_SCALE /
    OP_MERGE_SUM chain, src/llm.cpp:425-499; gate math
    src/nn/nn-cpu-ops.cpp:1462-1492 with normTopk=1.)

    Routing is dense over experts (every expert computes, outputs are
    masked by routing weight). That is compile-friendly and exact; the
    gather/ragged fast path for decode is `_moe_ffn_pallas`.

    Quantized expert weights (QuantWeight) are dequantized on the fly —
    one layer's experts at a time under the scan, so the transient is one
    [E, D, F] bf16 tensor, never the whole stack.
    """
    if isinstance(w1, QuantWeight):
        w1, w2, w3 = (dequant(w, x.dtype) for w in (w1, w2, w3))
    e = gate_w.shape[1]
    top_i, weights = _moe_route(x, gate_w, n_active)  # [B, T, k]

    # routing matrix [B, T, E]: normalized weight where selected, else 0
    routing = jnp.sum(
        jax.nn.one_hot(top_i, e, dtype=jnp.float32) * weights[..., None], axis=2
    )

    h1 = jnp.einsum("btd,edf->btef", x, w1)
    h3 = jnp.einsum("btd,edf->btef", x, w3)
    hidden = act(h1) * h3.astype(h1.dtype)
    expert_out = jnp.einsum("btef,efd->bted", hidden, w2)
    out = jnp.einsum(
        "bted,bte->btd", expert_out.astype(jnp.float32), routing
    )
    return out.astype(x.dtype)


def _moe_ffn_gather(
    x: jnp.ndarray,  # [B, T, D], B*T small (decode)
    gate_w: jnp.ndarray,  # [D, E]
    w1: jnp.ndarray,  # [E, D, F]
    w2: jnp.ndarray,  # [E, F, D]
    w3: jnp.ndarray,  # [E, D, F]
    n_active: int,
    act,
) -> jnp.ndarray:
    """Decode-path MoE: gather only the k active experts' weights and
    compute them, instead of running all E experts densely. For
    Qwen3-30B-A3B (8 of 128 experts) this cuts per-step expert FLOPs and
    HBM reads by ~16x. Same gate math as `_moe_ffn`.

    The reference computes exactly the active experts too (its MoE matmul
    walks the indexes buffer, nn-cpu-ops.cpp:1104-1136) — this is the
    XLA-gather restatement; the fully fused ragged kernel remains future
    work (SURVEY.md §7).
    """
    b, t, d = x.shape
    n = b * t
    xf = x.reshape(n, d)
    top_i, weights = _moe_route(xf, gate_w, n_active)  # [n, k]

    if isinstance(w1, QuantWeight):
        flat = top_i.reshape(-1)
        w1_sel, w2_sel, w3_sel = (
            dequant(
                QuantWeight(
                    jnp.take(w.q, flat, axis=0), jnp.take(w.d, flat, axis=0)
                ),
                x.dtype,
            )
            for w in (w1, w2, w3)
        )
    else:
        w1_sel = jnp.take(w1, top_i.reshape(-1), axis=0)  # [n*k, D, F]
        w3_sel = jnp.take(w3, top_i.reshape(-1), axis=0)
        w2_sel = jnp.take(w2, top_i.reshape(-1), axis=0)  # [n*k, F, D]
    k = n_active
    w1_sel = w1_sel.reshape(n, k, *w1_sel.shape[1:])
    w3_sel = w3_sel.reshape(n, k, *w3_sel.shape[1:])
    w2_sel = w2_sel.reshape(n, k, *w2_sel.shape[1:])

    hidden = act(jnp.einsum("nd,nkdf->nkf", xf, w1_sel))
    hidden = hidden * jnp.einsum("nd,nkdf->nkf", xf, w3_sel).astype(hidden.dtype)
    expert_out = jnp.einsum("nkf,nkfd->nkd", hidden, w2_sel)
    out = jnp.einsum(
        "nkd,nk->nd", expert_out.astype(jnp.float32), weights
    )
    return out.reshape(b, t, d).astype(x.dtype)


# Largest B*T routed through the ragged Pallas kernel: decode-lane sized.
# Beyond this, dense all-expert compute wins back (at m*k approaching E the
# per-(token, choice) DMA schedule re-reads experts the dense path reads
# once).
MOE_PALLAS_MAX_TOKENS = 16


def _moe_ffn_pallas(
    x: jnp.ndarray,  # [B, T, D] with B*T <= MOE_PALLAS_MAX_TOKENS
    gate_w: jnp.ndarray,
    w1,  # [E, D, F] dense, or QuantWeight (q int8 [E, D, F] + d [E, D/32, F])
    w2,  # [E, F, D] (same)
    w3,  # [E, D, F] (same)
    n_active: int,
    mesh,
    interpret: bool = False,
    sync_quant: bool = False,
    dedup: bool = False,
) -> jnp.ndarray:
    """Decode-step MoE via the ragged Pallas kernel (ops/moe_kernel.py):
    each token's top-k expert ids drive the HBM->VMEM DMA schedule, so only
    active experts' weights are read — quantized blocks when the experts
    are stored Q40 (the reference's storage format, src/llm.cpp:425-499).
    TP: experts are hidden-dim sliced like the reference (w1/w3 row-split,
    w2 col-split, llm.cpp:450-487), so each shard computes its slice and
    the partial outputs psum over ICI; tokens (the engine's dp lanes) stay
    dp-sharded."""
    b, t, d = x.shape
    n = b * t
    xf = x.reshape(n, d)
    top_i, weights = _moe_route(xf, gate_w, n_active)  # [n, k]
    quantized = isinstance(w1, QuantWeight)
    # two-tier dedup (opt-in): when concurrent lanes share experts, a
    # small-grid grouped kernel reads each UNIQUE expert's tiles once.
    # The small grid must be sized statically BELOW the all-distinct
    # worst case to beat the ragged kernel's A DMA steps (static grids
    # pay empty steps' DMAs — docs/moe_decode_dedup.md), so a lax.cond
    # on the runtime unique count picks between the compiled variants.
    # The cap derives from the PER-SHARD token count (ii's local shape
    # inside a dp shard_map), else dp runs would always "fit" a grid
    # larger than their local ragged step count. Off by default pending
    # routing-correlation data from real MoE checkpoints (uniform
    # routing rarely satisfies u <= A/2).

    def _maybe_two_tier(ii, ragged_fn, grouped_fn):
        n_loc, k_loc = ii.shape
        cap = (n_loc * k_loc) // 2 if dedup and n_loc > 1 else 0
        if not cap:
            return ragged_fn()
        flat = jnp.sort(ii.reshape(-1))
        u = 1 + jnp.sum(flat[1:] != flat[:-1])
        return lax.cond(u <= cap, lambda: grouped_fn(cap), ragged_fn)

    if quantized:
        operands = (xf, w1.q, w1.d, w2.q, w2.d, w3.q, w3.d, top_i, weights)

        def run(xx, w1q, w1d, w2q, w2d, w3q, w3d, ii, wts):
            return _maybe_two_tier(
                ii,
                lambda: moe_active_experts_q40(
                    xx, w1q, w1d, w2q, w2d, w3q, w3d, ii, wts,
                    interpret=interpret,
                ),
                lambda cap: moe_grouped_experts_q40(
                    xx, w1q, w1d, w2q, w2d, w3q, w3d, ii, wts,
                    interpret=interpret, max_segments=cap,
                ).astype(jnp.float32),
            )

    else:
        operands = (xf, w1, w2, w3, top_i, weights)

        def run(xx, ww1, ww2, ww3, ii, wts):
            return _maybe_two_tier(
                ii,
                lambda: moe_active_experts(
                    xx, ww1, ww2, ww3, ii, wts, interpret=interpret
                ),
                lambda cap: moe_grouped_experts(
                    xx, ww1, ww2, ww3, ii, wts,
                    interpret=interpret, max_segments=cap,
                ).astype(jnp.float32),
            )

    if mesh is None or mesh.devices.size == 1:
        out = run(*operands)
    else:
        from ..utils.compat import shard_map_compat as shard_map
        from jax.sharding import PartitionSpec as P

        # tokens ride the dp axis (xf's flat axis folds in the dp-sharded
        # batch); expert weights ride tp exactly like the dense FFN
        tok = P("dp", None) if (n % mesh.shape.get("dp", 1) == 0 and n > 1) else P()
        row_q = P(None, None, "tp")  # w1/w3 values AND scales: F on lanes
        col_q = P(None, "tp", None)  # w2 values AND scales: F on sublanes
        if quantized:
            in_specs = (tok, row_q, row_q, col_q, col_q, row_q, row_q, tok, tok)
        else:
            in_specs = (tok, row_q, col_q, row_q, tok, tok)

        from ..parallel.collectives import psum_maybe_quantized

        def body(*args):
            return psum_maybe_quantized(run(*args), "tp", sync_quant)

        out = shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=tok,
            check_vma=False,
        )(*operands)
    return out.reshape(b, t, d).astype(x.dtype)


def _moe_ffn_grouped(
    x: jnp.ndarray,  # [B, T, D] prefill-scale B*T
    gate_w: jnp.ndarray,
    w1,  # [E, D, F] dense or QuantWeight
    w2,
    w3,
    n_active: int,
    mesh,
    interpret: bool = False,
    sync_quant: bool = False,
) -> jnp.ndarray:
    """Prefill MoE via the grouped active-expert kernel
    (ops/moe_kernel.moe_grouped_experts*): assignments sorted by expert,
    expert weights streamed once per overlapping row tile — FLOPs and
    HBM reads proportional to the ACTIVE experts, where the dense prefill
    path paid the full E/k factor (VERDICT r2 missing #3; reference
    active-only semantics src/nn/nn-cpu-ops.cpp:1104-1136). TP layout
    matches _moe_ffn_pallas: experts F-sliced over tp, partial outputs
    psum'd; routing and the schedule are computed per shard from the
    shard's tokens."""
    from ..ops.moe_kernel import (
        moe_grouped_experts,
        moe_grouped_experts_q40,
    )

    b, t, d = x.shape
    n = b * t
    xf = x.reshape(n, d)
    quantized = isinstance(w1, QuantWeight)
    # route ONCE, outside any shard_map (same as _moe_ffn_pallas): the
    # gate einsum + top_k would otherwise rerun per tp shard
    top_i, wts = _moe_route(xf, gate_w, n_active)

    def run(xx, ii, ww, *wargs):
        if quantized:
            w1q, w1d, w2q, w2d, w3q, w3d = wargs
            return moe_grouped_experts_q40(
                xx, w1q, w1d, w2q, w2d, w3q, w3d, ii, ww,
                interpret=interpret,
            )
        ww1, ww2, ww3 = wargs
        return moe_grouped_experts(
            xx, ww1, ww2, ww3, ii, ww, interpret=interpret
        )

    operands = (
        (xf, top_i, wts, w1.q, w1.d, w2.q, w2.d, w3.q, w3.d)
        if quantized
        else (xf, top_i, wts, w1, w2, w3)
    )
    if mesh is None or mesh.devices.size == 1:
        out = run(*operands)
    else:
        from ..utils.compat import shard_map_compat as shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.collectives import psum_maybe_quantized

        tok = P("dp", None) if (n % mesh.shape.get("dp", 1) == 0 and n > 1) else P()
        row_q = P(None, None, "tp")
        col_q = P(None, "tp", None)
        if quantized:
            in_specs = (tok, tok, tok, row_q, row_q, col_q, col_q, row_q, row_q)
        else:
            in_specs = (tok, tok, tok, row_q, col_q, row_q)

        def body(*args):
            return psum_maybe_quantized(run(*args), "tp", sync_quant)

        out = shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=tok,
            check_vma=False,
        )(*operands)
    return out.reshape(b, t, d).astype(x.dtype)


def forward(
    params: Params,
    h: LlmHeader,
    tokens: jnp.ndarray,  # [B, T] int32
    pos: jnp.ndarray,  # scalar int32, or [B] per-lane positions
    cache: KvCache,
    mesh=None,
    moe_gather_max_tokens: int = 0,
    attn_window: int = 0,
    attn_park_threshold: int = 0,
    logits_mode: str = "all",
    sync_quant: bool = False,
    moe_decode_dedup: bool = False,
) -> Tuple[jnp.ndarray, KvCache]:
    """Run the decoder on T tokens starting at absolute position `pos`.

    Returns (logits [B, T, V] f32, updated cache). Jit-safe: T is static,
    `pos` is a traced scalar. Layers run under `lax.scan` over the stacked
    layer parameters so compile time is O(1) in depth.

    `mesh` is only consulted by the quantized (Pallas) matmul path, which
    needs explicit shard_map partitioning; the dense path is GSPMD-managed
    and ignores it.

    `attn_window` (static) restricts attention reads to the first
    `attn_window` cache rows — the caller guarantees pos + T <= window.
    On a 128k-seq-len model decoding at position 1k this cuts per-step
    cache reads by 128x; cache writes still land in the full-length cache.

    `attn_park_threshold` (static, per-lane mode): lanes whose position is
    >= the threshold are PARKED — their cache writes land at that position
    (the engine's padding rows) but their attention queries are masked out
    entirely (position pushed strongly negative), so an idle or prefilling
    -elsewhere lane costs one skipped-compute block instead of a full
    cache scan, and its discarded output is exactly zero.

    `logits_mode` (static): "all" -> logits [B, T, V]; "last" -> [B, 1, V],
    computing the final norm + vocab matmul on the last chunk row only —
    prefill chunks only sample from their last row, and for small models
    the vocab matmul is a large fraction of chunk FLOPs (~25% on a
    1B/128k-vocab shape), which lands directly on TTFT.
    """
    b, t = tokens.shape
    # `pos` may be a [B] vector: each batch lane decodes at its own
    # position (independent request lanes — the continuous-batching
    # surface the reference's single-stream loop lacks)
    attn_pos = attn_positions(pos, attn_park_threshold, cache["k"].shape[3])

    x = params["embed"][tokens]  # [B, T, D] (reference: OP_EMBEDDING)

    cos, sin = rope_slices(params, pos, t)
    x, k_new, v_new = run_layers(
        x, params["layers"], cache["k"], cache["v"], h, pos, attn_pos,
        cos, sin, mesh=mesh, attn_window=attn_window,
        sync_quant=sync_quant, moe_gather_max_tokens=moe_gather_max_tokens,
        moe_decode_dedup=moe_decode_dedup,
    )
    logits = logits_head(x, params, h, mesh, logits_mode)
    return logits, {"k": k_new, "v": v_new}


def attn_positions(pos, attn_park_threshold: int, cache_len: int):
    """Attention-query positions from cache-write positions: per-lane
    vectors with a park threshold mask parked lanes out of attention
    entirely (sentinel strongly negative for every query row of a T-wide
    chunk, hence -cache_len). Shared by `forward` and the pipeline driver
    so the park semantics cannot drift between them."""
    if jnp.ndim(pos) == 1 and attn_park_threshold:
        return jnp.where(pos >= attn_park_threshold, -cache_len, pos)
    return pos


def rope_slices(params: Params, pos: jnp.ndarray, t: int):
    """cos/sin rows for a T-wide chunk at `pos` (scalar, or [B] per-lane
    positions -> per-lane gathered [B, T, hd/2] tables)."""
    if jnp.ndim(pos) == 1:
        positions = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        return params["rope_cos"][positions], params["rope_sin"][positions]
    cos = lax.dynamic_slice_in_dim(params["rope_cos"], pos, t, axis=0)
    sin = lax.dynamic_slice_in_dim(params["rope_sin"], pos, t, axis=0)
    return cos, sin


def logits_head(
    x, params: Params, h: LlmHeader, mesh, logits_mode: str,
    tp_axis: str | None = None,
):
    """Final norm + vocab matmul (reference: src/llm.cpp:560-599).

    `tp_axis`: manual-collective mode (pipeline stages): `wcls` is this
    shard's vocab slice; the local logits all-gather over the axis — the
    reference's logits gather-to-root (llm.cpp:599), moved on-chip."""
    if logits_mode not in ("all", "last"):
        raise ValueError(f"unknown logits_mode: {logits_mode!r}")
    if logits_mode == "last":
        x = x[:, -1:, :]
    y = rms_norm(x, params["final_norm"], h.norm_epsilon)
    wcls = params["wcls"]
    if tp_axis is not None:
        from ..ops.int8_matmul import i8matmul
        from ..ops.quant_matmul import qmatmul

        if isinstance(wcls, Int8Weight):
            local = i8matmul(y, wcls)
        elif isinstance(wcls, _QUANT_CLASSES):
            local = qmatmul(y, wcls)
        else:
            local = jnp.einsum(
                "btd,dv->btv", y.astype(jnp.float32),
                wcls.astype(jnp.float32),
            )
        return lax.all_gather(local, tp_axis, axis=-1, tiled=True)
    if isinstance(wcls, Int8Weight):
        return i8matmul_tp(y, wcls, "row", mesh)
    if isinstance(wcls, _QUANT_CLASSES):
        return qmatmul_tp(y, wcls, "row", mesh)
    return jnp.einsum(
        "btd,dv->btv", y.astype(jnp.float32), wcls.astype(jnp.float32)
    )


def run_layers(
    x: jnp.ndarray,  # [B, T, D]
    layers: Params,  # stacked per-layer params, [L, ...] leading axis
    k_cache: jnp.ndarray,  # [L, B, KH, S, hd]
    v_cache: jnp.ndarray,
    h: LlmHeader,
    pos: jnp.ndarray,  # scalar or [B]: cache-write positions
    attn_pos: jnp.ndarray,  # same, possibly park-masked (see forward)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mesh=None,
    attn_window: int = 0,
    sync_quant: bool = False,
    moe_gather_max_tokens: int = 0,
    moe_decode_dedup: bool = False,
    tp_axis: str | None = None,
    tp_n: int = 1,
    sp_axis: str | None = None,
    sp_n: int = 1,
):
    """`lax.scan` the decoder layers over x; returns (x, k_new, v_new).

    Factored out of `forward` so the pipeline-parallel driver
    (parallel/pipeline.py) can run a STAGE'S LOCAL layer slice with
    identical math — there `layers`/caches carry L/pp layers and
    mesh=None (each stage computes locally; activations ride ppermute).

    `tp_axis`/`tp_n`: MANUAL tensor parallelism for callers already
    inside a shard_map (a pipeline stage's tp group): weights arrive as
    this shard's local slices (out dims / tp_n for row splits, kv-heads /
    tp_n on the cache), kernels run locally, and col-split partial sums
    psum over `tp_axis` — the same collective placement qmatmul_tp's own
    shard_map produces on a flat mesh. Requires mesh=None.

    `sp_axis`/`sp_n`: MANUAL sequence parallelism (pp x sp): the caches
    arrive as this shard's LOCAL rows of the CYCLIC sequence layout
    (local row j holds global position j*sp_n + shard index — the
    layout that makes attention windows tile sp, _attention_sp_merge),
    queries stay full-width and replicated over the axis. Attention is
    the merged-stats math and cache writes land on owning shards via a
    fixed-width window update + validity gather (a chunk's rows spread
    over every shard). Requires mesh=None.
    """
    b, t = x.shape[0], x.shape[1]
    interleaved = h.rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1)
    act = silu if h.hidden_act == HiddenAct.SILU else gelu
    is_qwen3 = h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE)
    per_lane = jnp.ndim(pos) == 1
    if (tp_axis is not None or sp_axis is not None) and mesh is not None:
        raise ValueError("manual tp/sp (tp_axis/sp_axis) requires mesh=None")
    shard_s = k_cache.shape[3]  # local (per-sp-shard) sequence length
    # manual sp: the per-shard write window is t//sp_n (+1 for unaligned
    # chunk starts) local rows, capped at the whole local shard — a
    # capped window starts at 0 and still covers any chunk's overlap
    sp_win = min(t // sp_n + 1, shard_s) if sp_axis is not None else 0
    sp_idx = lax.axis_index(sp_axis) if sp_axis is not None else None
    # flat GSPMD path over an sp mesh: same cyclic layout, permuted
    # whole-axis indices (shard g%sp holds global row g at local g//sp,
    # i.e. axis index (g%sp)*shard_rows + g//sp)
    _sp_mesh = mesh.shape.get("sp", 1) if mesh is not None else 1
    _shard_rows = k_cache.shape[3] // _sp_mesh
    # per-shard head/out dims (tp_n=1 on the flat/GSPMD path)
    hq, hkv = h.n_heads // tp_n, h.n_kv_heads // tp_n
    # mesh tp size: per-shard shape checks (MoE kernel gate)
    _tp_n = mesh.shape.get("tp", 1) if mesh is not None else 1

    def mm(yy, w, role, sync=False):
        if tp_axis is not None:
            return _mm_manual(yy, w, role, tp_axis, sync and sync_quant)
        return _mm(yy, w, role, mesh, sync and sync_quant)

    def _cache_append(cache_l, val):
        """Write the chunk at each lane's position (reference: OP_SHIFT,
        src/nn/nn-cpu-ops.cpp:1419-1441) -> dynamic_update_slice on the
        head-major cache's S axis, vmapped over lanes when positions
        differ. `val` arrives [B, T, KH, hd] from the projection. An
        int8 cache (QuantKV) quantizes the rows once here and routes
        values and scales through the SAME positional writer (the scale
        leaf's trailing singleton keeps ranks equal)."""
        val = val.transpose(0, 2, 1, 3)  # [B, KH, T, hd]
        if isinstance(cache_l, QuantKV):
            qv, sv = quantize_kv_rows(val)
            return QuantKV(
                _positional_write(cache_l.q, qv),
                _positional_write(cache_l.s, sv),
            )
        return _positional_write(cache_l, val.astype(cache_l.dtype))

    def _positional_write(cache_l, val):
        if sp_axis is not None:
            return _cache_append_sp(cache_l, val)
        if _sp_mesh > 1:
            return _cache_append_cyclic(cache_l, val)
        if per_lane:
            return jax.vmap(
                lambda c, u, p: lax.dynamic_update_slice_in_dim(c, u, p, axis=1)
            )(cache_l, val, pos)
        return lax.dynamic_update_slice_in_dim(cache_l, val, pos, axis=2)

    def _cache_append_cyclic(cache_l, val):
        """Flat-mesh sp write in the cyclic layout: global row g lives at
        axis index (g % sp) * shard_rows + g // sp. T == 1 stays a single
        dynamic_update_slice at the permuted index; T > 1 scatters the
        chunk's rows to their permuted indices (GSPMD routes each row to
        its owning shard)."""

        def perm(g):
            return (g % _sp_mesh) * _shard_rows + g // _sp_mesh

        if t == 1:
            if per_lane:
                return jax.vmap(
                    lambda c, u, p: lax.dynamic_update_slice_in_dim(
                        c, u, perm(p), axis=1
                    )
                )(cache_l, val, pos)
            return lax.dynamic_update_slice_in_dim(
                cache_l, val, perm(pos), axis=2
            )
        rows = jnp.arange(t, dtype=jnp.int32)
        if per_lane:
            return jax.vmap(
                lambda c, u, p: c.at[:, perm(p + rows)].set(u)
            )(cache_l, val, pos)
        return cache_l.at[:, :, perm(pos + rows)].set(val)

    def _cache_append_sp(cache_l, val):
        """Owning-shard window write for the manual (pp x sp) path with
        the CYCLIC layout: this shard's local row j holds global position
        j*sp_n + sp_idx, so a chunk [p, p+T) touches a contiguous local
        range of <= T//sp_n + 1 rows; a fixed sp_win-row window at the
        clamped local start covers the whole overlap, per-row validity +
        a gather route each chunk row to its slot. O(T/sp rows) per
        shard — no whole-slab select, no cross-shard collective."""

        def write(c, u, p):  # c [KH, S_local, hd], u [KH, T, hd], p scalar
            jstart = jnp.clip(
                (p - sp_idx + sp_n - 1) // sp_n, 0, shard_s - sp_win
            )
            cur = lax.dynamic_slice_in_dim(c, jstart, sp_win, axis=1)
            gpos = (jstart + jnp.arange(sp_win, dtype=jnp.int32)) * sp_n + sp_idx
            r = gpos - p  # chunk row belonging at each window row
            ok = jnp.logical_and(r >= 0, r < t)
            gathered = jnp.take(u, jnp.clip(r, 0, t - 1), axis=1)
            upd = jnp.where(ok[None, :, None], gathered, cur)
            return lax.dynamic_update_slice_in_dim(c, upd, jstart, axis=1)

        if per_lane:
            return jax.vmap(write)(cache_l, val, pos)
        return jax.vmap(lambda c, u: write(c, u, pos))(cache_l, val)

    def layer_step(x, layer):
        lp, k_cache_l, v_cache_l = layer

        # -- attention block (reference: src/llm.cpp:263-403) --
        y = rms_norm(x, lp["att_norm"], h.norm_epsilon)
        if "wqkv" in lp:
            # fused q|k|v: one kernel launch reads y once (7 -> 4 launches
            # per decode layer at ~41 us fixed cost each on the tunneled
            # chip; docs/silicon_r03.md). The un-interleave factor is the
            # weight's own static metadata, not the mesh's tp — a fused-
            # load/mesh mismatch stays correct (just non-optimally laid
            # out) instead of silently permuting columns. Under manual tp
            # the shard's local slice is one interleave chunk (the shard-
            # major layout puts shard i's [q_i|k_i|v_i] in chunk i), so
            # the local split factor is fuse / tp_n.
            fw = lp["wqkv"]
            if fw.fuse % tp_n != 0:
                raise ValueError(
                    f"fused weight interleave {fw.fuse} incompatible with "
                    f"manual tp_n={tp_n}"
                )
            qkv = mm(y, fw.weight, "row")
            q, k, v = _split_fused(
                qkv, fw.fuse // tp_n, tuple(d // tp_n for d in fw.dims)
            )
            q = q.reshape(b, t, hq, h.head_dim)
            k = k.reshape(b, t, hkv, h.head_dim)
            v = v.reshape(b, t, hkv, h.head_dim)
        else:
            q = mm(y, lp["wq"], "row").reshape(b, t, hq, h.head_dim)
            k = mm(y, lp["wk"], "row").reshape(b, t, hkv, h.head_dim)
            v = mm(y, lp["wv"], "row").reshape(b, t, hkv, h.head_dim)
        if is_qwen3:
            q = qk_rms_norm(q, lp["q_norm"], h.norm_epsilon)
            k = qk_rms_norm(k, lp["k_norm"], h.norm_epsilon)
        q = apply_rope(q, cos, sin, interleaved)
        k = apply_rope(k, cos, sin, interleaved)

        k_cache_l = _cache_append(k_cache_l, k)
        v_cache_l = _cache_append(v_cache_l, v)

        if sp_axis is not None:
            # manual sp (cyclic layout): a global window (sp multiple) is
            # the local prefix window/sp on every shard; dequant AFTER
            # slicing so int8 caches keep windowed, int8-sized reads
            if attn_window and attn_window % sp_n:
                raise ValueError(
                    f"attn_window {attn_window} must be a multiple of "
                    f"sp={sp_n}"
                )
            w_rows = (
                attn_window // sp_n
                if attn_window and attn_window < shard_s * sp_n
                else 0
            )
            z = _attention_sp_merge(
                q,
                dequant_kv(_slice_kv(k_cache_l, w_rows), x.dtype),
                dequant_kv(_slice_kv(v_cache_l, w_rows), x.dtype),
                attn_pos, sp_axis, sp_n,
            ).reshape(b, t, hq * h.head_dim)
        else:
            # flat non-sp: plain prefix slice (QuantKV rides sliced-but-
            # quantized into _attention_tp, which dequants at entry); the
            # sp mesh path windows inside _attention_sp per shard
            w_flat = (
                attn_window
                if attn_window
                and attn_window < k_cache_l.shape[2]
                and _sp_mesh == 1
                else 0
            )
            z = _attention_tp(
                q,
                _slice_kv(k_cache_l, w_flat),
                _slice_kv(v_cache_l, w_flat),
                attn_pos, h.head_dim, mesh,
                attn_window=attn_window if _sp_mesh > 1 else 0,
            )
        x = x + mm(z, lp["wo"], "col", sync=True).astype(x.dtype)

        # -- FFN block (reference: src/llm.cpp:405-557) --
        y = rms_norm(x, lp["ffn_norm"], h.norm_epsilon)
        if h.arch == LlmArch.QWEN3_MOE:
            # decode (lane-sized B*T): the ragged Pallas kernel reads only
            # each token's active experts' weights — Q40 blocks when the
            # experts are stored quantized. Prefill / CPU: dense-over-
            # experts (XLA's jnp.take gather measured ~3x slower than even
            # dense, so the gather path stays opt-in via
            # moe_gather_max_tokens).
            from ..ops.moe_kernel import moe_pallas_supported

            _w1 = lp["w1"]
            _quantized = isinstance(_w1, QuantWeight)
            _itemsize = 1 if _quantized else _w1.dtype.itemsize
            _f = _w1.q.shape[-1] if _quantized else _w1.shape[-1]
            # the kernels run PER-SHARD under shard_map, so the VMEM/
            # tiling gate must see the per-shard F (= F / tp), not the
            # global one — a shape legal globally can have no Mosaic-legal
            # F block per shard
            pallas_ok = (
                h.hidden_act == HiddenAct.SILU
                and jax.default_backend() == "tpu"
                and _f % _tp_n == 0
                and moe_pallas_supported(
                    h.dim, _f // _tp_n, _quantized, _itemsize
                )
            )
            if pallas_ok:
                # decode-sized token counts take the per-(token, choice)
                # ragged kernel; prefill-scale takes the grouped kernel
                # (FLOPs proportional to selected experts, not all E).
                # Multi-lane decode DEDUP through the grouped kernel was
                # investigated for r4 and rejected: a Pallas grid is
                # static, so it must be sized for the all-distinct worst
                # case (~m*k steps) and Mosaic does not elide the empty
                # steps' repeated-index DMAs (docs/silicon_r03.md) — the
                # schedule collapses *compute* per unique expert but not
                # HBM reads. Analysis + the viable lax.cond two-tier
                # design: docs/moe_decode_dedup.md.
                if b * t <= MOE_PALLAS_MAX_TOKENS:
                    f = _moe_ffn_pallas(
                        y, lp["moe_gate"], lp["w1"], lp["w2"], lp["w3"],
                        h.n_active_experts, mesh, sync_quant=sync_quant,
                        dedup=moe_decode_dedup,
                    )
                else:
                    f = _moe_ffn_grouped(
                        y, lp["moe_gate"], lp["w1"], lp["w2"], lp["w3"],
                        h.n_active_experts, mesh, sync_quant=sync_quant,
                    )
            else:
                moe = (
                    _moe_ffn_gather
                    if b * t <= moe_gather_max_tokens
                    else _moe_ffn
                )
                f = moe(
                    y,
                    lp["moe_gate"],
                    lp["w1"],
                    lp["w2"],
                    lp["w3"],
                    h.n_active_experts,
                    act,
                )
            if tp_axis is not None:
                # manual tp: experts arrived F-sliced (same layout the
                # mesh path shards); the local partial outputs all-reduce
                # here instead of inside the helpers' shard_map
                f = lax.psum(f, tp_axis)
        elif "w13" in lp:
            # fused w1|w3: the SwiGLU pair shares its input and activation
            fw13 = lp["w13"]
            dl13 = mm(y, fw13.weight, "row")
            d1, l3 = _split_fused(
                dl13, fw13.fuse // tp_n, tuple(d // tp_n for d in fw13.dims)
            )
            d = act(d1)
            f = mm(d * l3.astype(d.dtype), lp["w2"], "col", sync=True)
        else:
            d = act(mm(y, lp["w1"], "row"))
            l = mm(y, lp["w3"], "row")
            f = mm(d * l.astype(d.dtype), lp["w2"], "col", sync=True)
        x = x + f.astype(x.dtype)
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = lax.scan(
        layer_step, x, (layers, k_cache, v_cache)
    )
    return x, k_new, v_new
