"""Tensor-parallel sharding rules for the params pytree and KV cache.

This module *is* the reference's TP design, restated declaratively:

| reference mechanism (src/llm.cpp:168-176)          | PartitionSpec here |
|----------------------------------------------------|--------------------|
| sliceRowMatmul on q/k/v (out-dim split)            | wq/wk/wv: (.., "tp") last (out) axis |
| sliceColMatmul on wo (in-dim split, partial sums)  | wo: ("tp", ..) in axis; XLA inserts the all-reduce the reference built from SYNC_NODE_SLICES + OP_MERGE_ADD |
| sliceRowMatmul on w1/w3, sliceColMatmul on w2      | same pattern on the FFN |
| sliceKvCache (kv-head split)                       | cache: kv-head axis over "tp" |
| sliceMultiHeadAtt (head split)                     | falls out of the q/k/v out-shards |
| sliceRowMatmul on wcls + logits gather-to-root     | wcls: vocab axis over "tp"; the gather is XLA's |
| replicated norms/gates/embedding broadcast         | PartitionSpec() |

The weight *splitters* (splitRowMatmulWeight etc., src/nn/nn-core.cpp:289-322)
and the TCP weight shipping (NnRootWeightLoader) collapse into
`jax.device_put(array, NamedSharding(mesh, spec))`.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..formats.model_file import LlmArch, LlmHeader


def param_spec_tree(h: LlmHeader) -> dict[str, Any]:
    """PartitionSpecs matching the params pytree from models/loader.py.

    The same specs cover every quantized device format's leaves: a
    QuantWeight/PackedQuantWeight/Int8Weight is a (values, scales) pytree
    whose leaves all keep the [in-ish, out] axis order — row split puts
    "tp" on the last (out) axis of both leaves, col split on the
    second-to-last. For the packed q40i4 layout the value leaf's in axis
    is in//2 and the scale leaf's is in//32; both divide by tp under the
    engine's 32*tp divisibility check, so the col shard boundaries stay
    nibble- and block-aligned."""
    moe = h.arch == LlmArch.QWEN3_MOE
    # stacked layer weights carry a leading layer axis; MoE adds an expert axis
    row = P(None, None, None, "tp") if moe else P(None, None, "tp")  # out split
    col = P(None, None, "tp", None) if moe else P(None, "tp", None)  # in split
    layers: dict[str, Any] = {
        "att_norm": P(),
        "ffn_norm": P(),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        # fused q|k|v / w1|w3 (loader fuse > 0): same row split — the
        # shard-major interleave makes the contiguous tp chunks each hold
        # one shard's slice of every constituent
        "wqkv": P(None, None, "tp"),
        "w13": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "w1": row,
        "w2": col,
        "w3": row,
    }
    if moe:
        layers["moe_gate"] = P()
    if h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        layers["q_norm"] = P()
        layers["k_norm"] = P()
    return {
        # vocab-sharded (the reference computes the embedding on the root
        # node only and broadcasts X — SYNC_WITH_ROOT, src/llm.cpp:256 —
        # i.e. it holds the whole table on one node; here each shard
        # holds V/tp rows and the lookup masks+psums). Replicating the
        # table costs 2.1 GB/chip at 70B (docs/70b_plan.md) for no win:
        # the psum payload is a [B, T, D] activation, noise next to it.
        "embed": P("tp", None),
        "wcls": P(None, "tp"),
        "final_norm": P(),
        "rope_cos": P(),
        "rope_sin": P(),
        "layers": layers,
    }


def cache_specs(h: LlmHeader, sp: bool = False, pp: bool = False) -> dict[str, P]:
    """KV cache [L, B, KH, S, hd] (head-major): batch over dp, kv-heads
    over tp (reference: sliceKvCache, src/nn/nn-core.cpp:211-218). With
    `sp` the sequence axis additionally shards over the sp mesh axis — the
    long-context layout ring/merged attention consumes
    (models/transformer._attention_sp). With `pp` the LAYER axis shards
    over pipeline stages (each stage owns its layer range's cache,
    parallel/pipeline.py)."""
    lead = "pp" if pp else None
    spec = (
        P(lead, "dp", "tp", "sp", None)
        if sp
        else P(lead, "dp", "tp", None, None)
    )
    return {"k": spec, "v": spec}


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_params_put(mesh: Mesh, h: LlmHeader):
    """A `put` hook for models/loader.load_params that places each tensor
    with its TP sharding as it is read — per-shard streaming, so host
    memory and per-device HBM stay at one slice per tensor (the TPU
    equivalent of the reference's slice-by-slice socket streaming,
    src/llm.cpp:614-669). On a mesh with a `pp` axis the layer-stacked
    tensors additionally shard their leading (layer) axis over stages."""
    specs = param_spec_tree(h)
    if "pp" in mesh.axis_names:
        from .pipeline import pp_param_specs

        specs = pp_param_specs(specs)
    flat_layer_specs = specs["layers"]

    def _spec(name: str) -> P:
        spec = specs.get(name) if name in specs else flat_layer_specs.get(name)
        return spec if spec is not None else P()

    def put(name: str, arr: np.ndarray):
        return jax.device_put(arr, NamedSharding(mesh, _spec(name)))

    # Streaming hook: the loader asks for a tensor's sharding UP FRONT and
    # pulls each device shard's bytes lazily (make_array_from_callback)
    # instead of materializing whole layer stacks on host — see
    # models/loader._stream_quant_stack.
    put.sharding = lambda name: NamedSharding(mesh, _spec(name))
    return put
