"""Quantized collective payloads — the reference's Q80 sync buffer, SPMD-style.

The reference cuts cross-node sync traffic to ~26% of f32 by quantizing
the ZQ activation pipe to Q80 (int8 values + per-32-block scales) before
every SYNC_NODE_SLICES all-gather, then dequantizing and summing locally
(--buffer-float-type q80; src/llm.cpp:195, README.md:89). Its all-reduce
IS that all-gather + local OP_MERGE_ADD sum (src/nn/nn-cpu-ops.cpp:920-957)
— which is exactly reproducible under shard_map:

    psum_q80(x) = sum over participants of dequant(all_gather(quant(x)))

Payload per element: 1 B values + 4/32 B scales = 1.125 B vs 4 B f32
(~28%). Over single-host ICI the compression is unnecessary (ICI bandwidth
dwarfs the payload; the exact f32 psum is the default) — the win is on
DCN-connected multi-host pods, the same regime the reference built Q80
sync for on 1 GbE clusters.

Quantization error matches the reference's regime: int8 rounding against a
per-32-block amax scale (the reference uses the identical block structure;
its scales are f16, ours f32 — scale traffic is 3% of payload either way).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Q_BLOCK = 32


def quantize_q80_blocks(x: jnp.ndarray):
    """Per-32-block symmetric int8 quantization along the LAST axis.

    Returns (q int8 [..., n], scale f32 [..., n // 32]). Matches the
    reference's Q80 block structure (NnBlockQ80, src/nn/nn-quants.hpp:69-72):
    scale = amax / 127, q = round(x / scale). All-zero blocks quantize to
    scale 0 / q 0."""
    *lead, n = x.shape
    assert n % Q_BLOCK == 0, f"last dim {n} not divisible by {Q_BLOCK}"
    xf = x.astype(jnp.float32).reshape(*lead, n // Q_BLOCK, Q_BLOCK)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(*lead, n), scale


def dequantize_q80_blocks(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of `quantize_q80_blocks`; f32 [..., n]."""
    *lead, n = q.shape
    qf = q.astype(jnp.float32).reshape(*lead, n // Q_BLOCK, Q_BLOCK)
    return (qf * scale[..., None]).reshape(*lead, n)


def psum_q80(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-reduce with Q80-quantized payload: each participant quantizes
    its partial sum, all-gathers the int8 blocks + scales, and sums the
    dequantized shards locally — byte-for-byte the reference's
    SYNC_NODE_SLICES(q80 ZQ pipe) + OP_MERGE_ADD design. Call under
    shard_map. Returns f32 in x's shape."""
    q, scale = quantize_q80_blocks(x)
    qg = lax.all_gather(q, axis_name)  # [n_dev, ..., n]
    sg = lax.all_gather(scale, axis_name)
    return jnp.sum(dequantize_q80_blocks(qg, sg), axis=0).astype(x.dtype)


def psum_maybe_quantized(
    x: jnp.ndarray, axis_name: str, quantized: bool
) -> jnp.ndarray:
    """`lax.psum` (exact, the ICI default) or `psum_q80` (compressed, the
    DCN/multi-host payload the reference calls --buffer-float-type q80)."""
    if quantized:
        return psum_q80(x, axis_name)
    return lax.psum(x, axis_name)
