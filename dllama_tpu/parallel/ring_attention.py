"""Ring attention: causal attention with the KV sequence sharded over chips.

Long-context/sequence parallelism the reference does not have (SURVEY.md §2
lists SP/CP/ring as absent; §5 marks it the biggest upgrade surface): when a
context no longer fits one chip's HBM, the KV cache shards along the
SEQUENCE axis over the `sp` mesh axis and attention runs as a ring:

  * every chip holds one Q shard (its slice of query positions) and one KV
    shard (its slice of the sequence);
  * sp steps: each chip computes blockwise attention of its Q shard against
    the KV shard currently resident, accumulating online-softmax partial
    state (m, l, acc); after each step the KV shard rotates one hop around
    the ring via `lax.ppermute` over ICI;
  * causality falls out of absolute positions: a KV block from a later part
    of the sequence than a query contributes nothing (fully masked), so the
    combine is exact, not approximate.

The partial-state combine is the standard log-sum-exp merge:
    m' = max(m1, m2); l' = e^{m1-m'} l1 + e^{m2-m'} l2
    acc' = e^{m1-m'} acc1 + e^{m2-m'} acc2

The local step has two backends: the shared jnp einsum math (correct on any
backend; XLA overlaps the ppermute with compute) and the Pallas flash-stats
kernel (ops/flash_attention.flash_attention_stats), auto-selected on TPU
when the shard shapes tile cleanly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


from ..ops.jnp_ops import attention_stats as _stats_jnp


def _local_attention_stats(
    q, k, v, q_pos0, s_pos0, use_flash: bool = False, interpret: bool = False,
    s_stride: int = 1,
):
    """Per-shard causal-GQA partial state: the Pallas flash-stats kernel when
    requested (TPU hot path — blockwise, no [Tq, Ss] score buffer), else the
    shared jnp math (ops/jnp_ops.attention_stats). Both backends support
    `s_stride` > 1 (cyclic sequence layouts: key row j at position
    s_pos0 + j*stride) and an int8 `QuantKV` shard — the kernel consumes
    it natively (per-row scales dequant on the VMEM tile; int8-sized HBM
    reads AND int8-sized ring ppermute payloads), the jnp path dequants."""
    if use_flash:
        from ..ops.flash_attention import flash_attention_stats

        return flash_attention_stats(
            q, k, v, q_pos0, s_pos0, interpret=interpret,
            s_stride=s_stride,
        )
    from ..ops.kv_cache import dequant_kv

    return _stats_jnp(
        q, dequant_kv(k, q.dtype), dequant_kv(v, q.dtype), q_pos0, s_pos0,
        s_stride=s_stride,
    )


def _merge_stats(acc1, m1, l1, acc2, m2, l2):
    """Log-sum-exp merge of two online-softmax partial states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # fully-masked states (m == -inf) contribute nothing
    a1 = jnp.where(m1 <= _NEG_INF / 2, 0.0, a1)
    a2 = jnp.where(m2 <= _NEG_INF / 2, 0.0, a2)
    return (
        acc1 * a1[..., None] + acc2 * a2[..., None],
        m,
        l1 * a1 + l2 * a2,
    )


def ring_attention_local(
    q: jnp.ndarray,  # [B, Tq, H, hd] this chip's query shard
    k: jnp.ndarray,  # [B, KH, Ss, hd] this chip's KV shard (head-major)
    v: jnp.ndarray,
    q_pos0: jnp.ndarray,  # absolute position of this chip's first query
    shard_size: jnp.ndarray,  # sequence length held per chip (Ss)
    axis_name: str = "sp",
    use_flash: bool = False,
    interpret: bool = False,
    cyclic: bool = False,
) -> jnp.ndarray:
    """Per-shard ring attention body; call under shard_map with the sequence
    axis of q/k/v sharded over `axis_name`. Returns [B, Tq, H, hd].

    `cyclic`: the KV shards use the cyclic sequence layout (shard i's row
    j holds global position j*sp + i — the layout that lets attention
    windows tile sp shards, see engine._attn_window): key positions of
    the shard owned by `owner` are then owner + arange*sp instead of the
    contiguous owner*shard_size + arange. Both the jnp and flash-stats
    local steps handle the stride."""
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    stride = sp if cyclic else 1

    def step(carry, _):
        k_cur, v_cur, owner, acc, m, l = carry
        s_pos0 = owner if cyclic else owner * shard_size
        acc2, m2, l2 = _local_attention_stats(
            q, k_cur, v_cur, q_pos0, s_pos0, use_flash, interpret,
            s_stride=stride,
        )
        acc, m, l = _merge_stats(acc, m, l, acc2, m2, l2)
        # rotate KV one hop: chip i sends to chip (i+1) % sp, so the shard
        # owned by (idx - step - 1) arrives next
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        owner = (owner - 1) % sp
        return (k_nxt, v_nxt, owner, acc, m, l), None

    b, tq, h, hd = q.shape
    kh = k.shape[1]
    g = h // kh
    acc0 = jnp.zeros((b, kh, g, tq, hd), jnp.float32)
    m0 = jnp.full((b, kh, g, tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kh, g, tq), jnp.float32)

    # sp-1 compute+rotate steps, then one final compute — the last shard's
    # rotation would be discarded, so don't pay that ICI hop
    carry = (k, v, idx, acc0, m0, l0)
    if sp > 1:
        carry, _ = lax.scan(step, carry, None, length=sp - 1)
    k_last, v_last, owner, acc, m, l = carry
    acc2, m2, l2 = _local_attention_stats(
        q, k_last, v_last, q_pos0,
        owner if cyclic else owner * shard_size,
        use_flash, interpret, s_stride=stride,
    )
    acc, m, l = _merge_stats(acc, m, l, acc2, m2, l2)

    # normalize; rows with no visible keys (can't happen for causal pos>=0
    # queries, but keep the guard) -> 0
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]  # [b, kh, g, tq, hd]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, tq, h, hd)
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, hd] global queries
    k: jnp.ndarray,  # [B, KH, S, hd] global keys (S = T for self-attention)
    v: jnp.ndarray,
    mesh,
    q_pos0: int | jnp.ndarray = 0,
    axis_name: str = "sp",
    use_flash: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Driver: shards the sequence axis of q/k/v over `axis_name`, runs the
    ring, returns globally-assembled [B, T, H, hd].

    Requires T % sp == 0 and S % sp == 0. Head axes stay whole here; combine
    with the tp axis by nesting specs when both are in play.
    """
    from ..utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    sp = mesh.shape[axis_name]
    b, t, h, hd = q.shape
    s = k.shape[2]
    assert t % sp == 0 and s % sp == 0, (t, s, sp)
    shard_size = s // sp
    tq = t // sp
    if use_flash is None:
        from ..ops.flash_attention import pick_flash_blocks

        use_flash = (
            jax.default_backend() == "tpu"
            and pick_flash_blocks(tq, shard_size) is not None
        )

    def body(qq, kk, vv):
        idx = lax.axis_index(axis_name)
        return ring_attention_local(
            qq,
            kk,
            vv,
            q_pos0=q_pos0 + idx * tq,
            shard_size=shard_size,
            axis_name=axis_name,
            use_flash=use_flash,
            interpret=interpret,
        )

    q_spec = P(None, axis_name, None, None)
    kv_spec = P(None, None, axis_name, None)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )(q, k, v)
