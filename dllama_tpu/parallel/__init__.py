from .mesh import make_mesh, validate_tp
from .sharding import param_spec_tree, cache_specs, shard_params_put, named_sharding

__all__ = [
    "make_mesh",
    "validate_tp",
    "param_spec_tree",
    "cache_specs",
    "shard_params_put",
    "named_sharding",
]
