"""Device mesh construction for tensor/data parallel inference.

TPU-native replacement for the reference's cluster topology: where the
reference bootstraps a full TCP socket mesh of 2^n root+worker processes
(NnNetwork::connect/serve, src/nn/nn-network.cpp:295-379) and ships op
graphs to workers, here every chip runs the same SPMD program under one
controller and the "topology" is a `jax.sharding.Mesh` whose collectives
ride ICI (multi-host: DCN via `jax.distributed.initialize`, see
`initialize_multihost`).

Axes:
    dp — data parallel over the batch axis (the reference has no DP;
         surfaced here because it is free under SPMD)
    tp — tensor parallel: matmul row/col splits, kv-head-split attention,
         mirroring the reference's slicing (src/nn/nn-core.cpp:211-285)
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from ..formats.model_file import LlmHeader


def reassert_platform() -> None:
    """Re-assert the JAX_PLATFORMS env choice through the config API.

    This environment's TPU platform plugin wins over the env var in some
    import orders, and with the tunnel down the plugin probe can hang —
    every entry point that honors JAX_PLATFORMS must call this before
    touching devices. Raises if the requested platform can't be set (a
    silent fallback would benchmark/run on the wrong backend)."""
    import os

    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        jax.config.update("jax_platforms", requested)


def enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compilation cache: decode/prefill programs survive
    process restarts (first TPU compile costs 20-40s; the reference has no
    compilation to cache, but its 'workers receive prebuilt graphs' startup
    is the analogous amortization). Respects JAX_COMPILATION_CACHE_DIR."""
    import os

    if jax.config.jax_compilation_cache_dir:
        return  # the user already configured a cache; don't clobber it
    path = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.expanduser("~/.cache/dllama_tpu/xla")
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        pass  # cache is an optimization; never fail startup over it


def validate_tp(h: LlmHeader, tp: int) -> None:
    """Mirror the reference's shardability constraints (src/app.cpp:236-240
    requires nNodes ≤ nKvHeads and 2^n nodes; the dimension divisibility
    asserts live in its slicers, src/nn/nn-core.cpp:211-243)."""
    if tp < 1 or (tp & (tp - 1)) != 0:
        raise ValueError(f"tp must be a power of two, got {tp}")
    if tp > h.n_kv_heads:
        raise ValueError(
            f"tp={tp} exceeds nKvHeads={h.n_kv_heads} (the KV cache shards "
            "by kv head, like the reference's sliceKvCache)"
        )
    for name, dim in [
        ("dim", h.dim),
        ("qDim", h.q_dim),
        ("kvDim", h.kv_dim),
        ("hiddenDim", h.ff_dim),
        ("vocabSize", h.vocab_size),
    ]:
        if dim % tp != 0:
            raise ValueError(f"{name}={dim} not divisible by tp={tp}")


def auto_tp(model_path: str, n_devices: int | None = None) -> int:
    """Largest power-of-two tp that both the device count and the model's
    shardability constraints allow (mirrors the reference's
    nNodes <= nKvHeads rule, src/app.cpp:236-238). Shared by the CLI and
    the API server."""
    from ..formats.model_file import read_llm_header

    if n_devices is None:
        n_devices = len(jax.devices())
    h = read_llm_header(model_path)
    tp = 1
    while tp * 2 <= n_devices:
        try:
            validate_tp(h, tp * 2)
        except ValueError:
            break
        tp *= 2
    return tp


def make_mesh(
    tp: int = 1, dp: int = 1, sp: int = 1, pp: int = 1, devices=None
) -> Mesh:
    """Build a (pp, dp, sp, tp) mesh over the available devices.

    `sp` is the sequence/context-parallel axis (ring attention); `pp` the
    pipeline-stage axis (layer ranges per stage, parallel/pipeline.py —
    the axis that lifts the reference's nNodes <= nKvHeads ceiling on
    cluster size). Each axis only appears in the mesh when > 1 so
    existing PartitionSpecs stay valid. Uses `jax.experimental.mesh_utils`
    device ordering so the tp axis maps to physically adjacent chips
    (fastest ICI hops) on real TPU slices; pp is outermost — stage
    hand-offs are the rarest, smallest transfers.
    """
    if devices is None:
        devices = jax.devices()
    n_needed = tp * dp * sp * pp
    if n_needed > len(devices):
        raise ValueError(
            f"need {n_needed} devices (pp={pp} x tp={tp} x dp={dp} x "
            f"sp={sp}), have {len(devices)}"
        )
    shape = (dp, sp, tp) if sp > 1 else (dp, tp)
    names = ("dp", "sp", "tp") if sp > 1 else ("dp", "tp")
    if pp > 1:
        shape = (pp,) + shape
        names = ("pp",) + names
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(
            shape, devices=devices[:n_needed]
        )
    except Exception:
        import numpy as np

        device_array = np.asarray(devices[:n_needed]).reshape(shape)
    return Mesh(device_array, axis_names=names)


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host (DCN) bootstrap — the SPMD analogue of the reference's
    root/worker handshake (src/nn/nn-network.cpp:295-379). On a TPU pod
    slice all arguments are auto-detected from the TPU metadata; elsewhere
    pass them explicitly."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
