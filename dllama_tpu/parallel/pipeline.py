"""Pipeline parallelism: decoder layers sharded into stages over a `pp`
mesh axis.

The reference cannot scale past `nNodes <= nKvHeads` — its only cross-node
strategy is tensor parallelism, bounded by the KV-head count (SURVEY.md §2
parallelism checklist; src/app.cpp:236-240). Pipeline stages lift that
ceiling: each stage holds a contiguous range of L/pp layers (weights AND
that range's KV cache), activations hop stage-to-stage over ICI
(`lax.ppermute` of one [B, T, D] tensor — the smallest inter-chip payload
in the whole model), and the per-stage HBM footprint shrinks by pp. A
70B+ checkpoint that cannot fit tp<=8 chips runs as pp stages of tp
groups.

Schedule (inference forward, single microbatch): P pipeline ticks; at
tick i stage i runs its local layer scan on the activation it received,
every other stage computes the same program on pass-through data and
discards it (SPMD requires identical programs; the discarded compute is
the classic pipeline bubble). Latency per forward is the same L layer
steps the single-device program pays — the bubble costs device
*utilization*, not request latency, so for fit-constrained serving the
trade is free. Stage-local math is `models.transformer.run_layers` —
bit-identical to the single-device path.

Caches: the [L, ...] KV cache shards its LAYER axis over pp (each stage
owns its range's cache); a stage's cache only commits on its active tick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..formats.model_file import LlmHeader


def validate_pp(h: LlmHeader, pp: int) -> None:
    """Any pp >= 1 that divides the layer count works (the ring ppermute
    schedule has no power-of-two requirement — 80 layers over 5 stages is
    legal, unlike the reference's 2^n node rule)."""
    if pp < 1:
        raise ValueError(f"pp must be >= 1, got {pp}")
    if pp > 1 and h.n_layers % pp != 0:
        raise ValueError(
            f"nLayers={h.n_layers} not divisible by pp={pp} (stages hold "
            "equal layer ranges)"
        )


def pp_param_specs(base: dict) -> dict:
    """Layer-stacked params shard the stage axis on their leading (layer)
    dim; global tensors (embed, wcls, norms, rope) stay replicated on pp.
    `base` is parallel.sharding.param_spec_tree output (tp rules), whose
    layers-tree specs lead with the layer axis (None or empty = the
    replicated norms) — pp takes that axis over."""

    def with_pp(spec):
        tup = tuple(spec)
        if not tup:
            return P("pp")
        if tup[0] is not None:
            raise ValueError(
                f"layers leaf's leading (layer) axis is already sharded "
                f"({spec}); pp cannot take it over"
            )
        return P(*(("pp",) + tup[1:]))

    out = dict(base)
    out["layers"] = {k: with_pp(spec) for k, spec in base["layers"].items()}
    return out


def forward_pp(
    params,
    h: LlmHeader,
    tokens: jnp.ndarray,  # [B, T] int32
    pos: jnp.ndarray,  # scalar or [B]
    cache,  # {"k","v"}: [L, B, KH, S, hd], layer axis pp-sharded
    mesh,
    attn_window: int = 0,
    attn_park_threshold: int = 0,
    logits_mode: str = "all",
    n_micro: int = 1,
    sync_quant: bool = False,
    park_pos: int = 0,
    moe_decode_dedup: bool = False,
):
    """Pipeline-parallel forward: same contract as models.forward.

    Stage-local compute runs with mesh=None (plain kernels, no nested
    shard_map). When the mesh also carries a `tp` axis, each stage is a
    TENSOR-PARALLEL GROUP: weights arrive row/col-sliced per the same
    PartitionSpecs the flat mesh uses (pp_param_specs over
    param_spec_tree), kernels run on the local slices, and the col-split
    partial sums / MoE outputs psum over "tp" INSIDE the stage
    (run_layers tp_axis) — pp x tp is how a 70B+ checkpoint outgrows the
    tp <= nKvHeads ceiling: stages of tp groups. A `dp` mesh axis
    additionally shards the batch lanes inside every stage (tokens, pos,
    cache batch axis, logits all dp-split): pp x dp is the pipeline's
    throughput configuration — lockstep pp decode throughput is set by
    concurrent lanes (docs/pp_decode_model.md), and dp multiplies lanes
    without growing any single chip's batch. sp composition is handled
    via manual stats-merge attention (sp_axis). The manual partial-sum
    order differs from the flat
    mesh's single reduction, so low-precision (bf16) greedy streams can
    flip argmax near-ties on near-uniform logits — the same neutral
    divergence class any tensor-parallel partial summing has (f32 runs
    match the flat mesh exactly; tests pin that).

    `n_micro` > 1 splits the CHUNK (T) axis into sequence-wave
    microbatches, GPipe-style: at tick t stage s processes chunk t - s,
    so all stages work concurrently on successive chunks once the
    pipeline fills — utilization n_micro / (pp + n_micro - 1) instead of
    1/pp. Causality holds because chunk c reaches stage s only after
    chunks < c committed their KV rows at that stage (earlier ticks).
    Prefill is compute-bound, so this is where the pp bubble actually
    costs time; decode (T=1, weight-bandwidth-bound) keeps n_micro=1 —
    splitting lanes into groups would re-read the stage's weights per
    group and erase the batching win. Requires T % n_micro == 0.

    `park_pos` > 0 routes INVALID ticks' cache writes into the lane-
    padding rows at that index (the same scratch rows lane parking uses)
    instead of select-merging the whole stage cache every tick. The
    per-tick `jnp.where(valid, k_new, k_c)` reads+writes the stage's
    entire [L/pp, B, KH, S, hd] cache — on an 8B/pp=4 layout that is
    ~130 MB x2 moved per tick, comparable to the stage's weight read
    itself — while the park write touches only T rows. Causality is
    preserved because padding rows sit at indices > every real position,
    so the causal mask already excludes them from attention (identical
    to the engine's lane-parking argument). Requires the cache's S axis
    to carry >= chunk-width padding beyond `park_pos`.
    """
    from ..utils.compat import shard_map_compat as shard_map

    from ..models.transformer import (
        attn_positions,
        logits_head,
        rope_slices,
        run_layers,
    )

    pp = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    sp = mesh.shape.get("sp", 1)
    # dp: batch lanes shard over the dp axis INSIDE each stage — the
    # pipeline's throughput lever (docs/pp_decode_model.md: lockstep pp
    # decode throughput scales with concurrent lanes, and dp multiplies
    # lanes without growing any one chip's batch). sp: the cache's
    # sequence axis shards inside each stage; attention runs the manual
    # merged-stats math (run_layers sp_axis).
    b, t = tokens.shape
    if t % n_micro != 0:
        raise ValueError(f"T={t} not divisible by n_micro={n_micro}")
    tc = t // n_micro
    cache_s = cache["k"].shape[3]
    if park_pos and park_pos + tc > cache_s:
        # dynamic_update_slice clamps out-of-range starts silently, which
        # would divert the scratch writes onto the LAST REAL ROWS — make
        # the missing-padding case loud instead (the engine sizes the
        # cache with >= max-bucket padding whenever pp > 1)
        raise ValueError(
            f"park_pos={park_pos} needs {tc} scratch rows but the cache "
            f"sequence axis has only {cache_s} rows; allocate "
            f">= park_pos + chunk width"
        )
    attn_pos = attn_positions(pos, attn_park_threshold, cache_s)
    per_lane = jnp.ndim(pos) == 1

    layers = params["layers"]
    globals_ = {
        k: params[k]
        for k in ("embed", "wcls", "final_norm", "rope_cos", "rope_sin")
    }

    sp_ax = "sp" if sp > 1 else None
    if tp > 1:
        # per-leaf pp x tp specs: leading layer axis over stages, row/col
        # matmul splits over the stage's tp group (the flat mesh's rules,
        # parallel/sharding.param_spec_tree, pp-prefixed)
        from ..parallel.sharding import param_spec_tree

        all_specs = param_spec_tree(h)
        layer_specs = pp_param_specs(all_specs)["layers"]
        layers_spec = {k: layer_specs[k] for k in layers}
        cache_spec = P("pp", "dp", "tp", sp_ax, None)
        # wcls keeps its vocab-axis tp shard (pp-replicated): each stage's
        # tp group computes its vocab slice and all-gathers inside the
        # body (logits_head tp_axis) — passing it replicated would
        # re-all-gather the full vocab matrix onto every chip per step
        globals_spec = {k: all_specs[k] for k in globals_}
    else:
        layers_spec = P("pp")  # prefix: leading (layer) axis of every leaf
        cache_spec = P("pp", "dp", None, sp_ax, None)
        globals_spec = P()
    repl = P()
    # batch lanes shard over dp inside each stage (specs work for dp=1
    # too — the axis always exists on a pp mesh, parallel/mesh.make_mesh)
    tok_spec = P("dp", None)
    pos_spec = P("dp") if per_lane else P()
    logits_spec = P("dp", None, None)
    ring = [(i, (i + 1) % pp) for i in range(pp)]

    # logits_mode="last" (every prefill/decode step) only consumes the
    # final chunk's rows: keep a [B, tc, D] exit register instead of the
    # [B, T, D] buffer, shrinking both the HLO live range and the final
    # cross-stage psum payload by a factor of n_micro
    keep_all = logits_mode == "all"

    def body(layers, k_c, v_c, globals_, tokens, pos, attn_pos):
        stage = lax.axis_index("pp")
        d = globals_["embed"].shape[-1]
        bl = tokens.shape[0]  # dp-local batch lanes
        x0 = jnp.zeros((bl, tc, d), globals_["embed"].dtype)  # stage register
        done0 = jnp.zeros((bl, t if keep_all else tc, d), x0.dtype)

        def embed_lookup(ids):
            # vocab-sharded table under tp (param_spec_tree): each shard
            # gathers its local rows, out-of-range ids contribute zero,
            # psum assembles the [B, tc, D] rows — same manual move the
            # flat path gets from GSPMD's partitioned gather
            emb = globals_["embed"]
            if tp > 1:
                vloc = emb.shape[0]
                loc = ids - lax.axis_index("tp") * vloc
                ok = jnp.logical_and(loc >= 0, loc < vloc)
                rows = emb[jnp.clip(loc, 0, vloc - 1)]
                return lax.psum(
                    jnp.where(ok[..., None], rows, jnp.zeros_like(rows)),
                    "tp",
                )
            return emb[ids]

        def tick_body(tick, carry):
            # stage s processes chunk c = tick - s this tick (when valid);
            # stage 0 injects chunk `tick`'s embedding first. One traced
            # instance of the stage program serves every tick (the
            # schedule runs under fori_loop — unrolling would inline
            # pp + n_micro - 1 copies of the layer scan per compile).
            x, x_done, k_c, v_c = carry
            inj = lax.dynamic_slice_in_dim(
                tokens, jnp.clip(tick * tc, 0, t - tc), tc, axis=1
            )
            x = jnp.where(
                jnp.logical_and(stage == 0, tick < n_micro),
                embed_lookup(inj),
                x,
            )
            c = tick - stage
            valid = jnp.logical_and(c >= 0, c < n_micro)
            c_safe = jnp.clip(c, 0, n_micro - 1)
            pos_c = pos + c_safe * tc
            attn_pos_c = attn_pos + c_safe * tc
            if park_pos:
                # invalid ticks write their (garbage) chunk into the
                # padding scratch rows; real rows are untouched, so the
                # O(stage cache) select below collapses to a no-op
                pos_c = jnp.where(valid, pos_c, park_pos)
            cos, sin = rope_slices(globals_, pos_c, tc)
            x_out, k_new, v_new = run_layers(
                x, layers, k_c, v_c, h, pos_c, attn_pos_c, cos, sin,
                mesh=None, attn_window=attn_window,
                sync_quant=sync_quant,
                moe_decode_dedup=moe_decode_dedup,
                tp_axis="tp" if tp > 1 else None, tp_n=tp,
                sp_axis=sp_ax, sp_n=sp,
            )
            # commit this stage's cache range only for a valid chunk;
            # invalid ticks computed on pass-through/fill data (park mode:
            # their writes already landed in scratch rows)
            if park_pos:
                k_c, v_c = k_new, v_new
            else:
                # tree_map: an int8 cache is a QuantKV (values, scales) pair
                sel = lambda a, b: jnp.where(valid, a, b)  # noqa: E731
                k_c = jax.tree.map(sel, k_new, k_c)
                v_c = jax.tree.map(sel, v_new, v_c)
            x = jnp.where(valid, x_out, x)
            # a chunk finishing the LAST stage exits into the output
            # register (every stage computes the update; only the last
            # stage's is kept)
            exited = jnp.logical_and(valid, stage == pp - 1)
            if keep_all:
                x_done = jnp.where(
                    exited,
                    lax.dynamic_update_slice_in_dim(
                        x_done, x, c_safe * tc, axis=1
                    ),
                    x_done,
                )
            else:  # only the final chunk's rows feed logits_mode="last"
                x_done = jnp.where(
                    jnp.logical_and(exited, c == n_micro - 1), x, x_done
                )
            # hand the register to the next stage
            x = lax.ppermute(x, "pp", ring)
            return x, x_done, k_c, v_c

        _, x_done, k_c, v_c = lax.fori_loop(
            0, pp + n_micro - 1, tick_body, (x0, done0, k_c, v_c)
        )
        # collect the output from the last stage onto every stage
        x_done = lax.psum(
            jnp.where(stage == pp - 1, x_done, jnp.zeros_like(x_done)), "pp"
        )
        logits = logits_head(
            x_done, globals_, h, None, logits_mode,
            tp_axis="tp" if tp > 1 else None,
        )
        return logits, k_c, v_c

    logits, k_new, v_new = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            layers_spec, cache_spec, cache_spec, globals_spec, tok_spec,
            pos_spec, pos_spec,
        ),
        out_specs=(logits_spec, cache_spec, cache_spec),
        check_vma=False,
    )(layers, cache["k"], cache["v"], globals_, tokens, pos, attn_pos)
    return logits, {"k": k_new, "v": v_new}
