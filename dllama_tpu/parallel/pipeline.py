"""Pipeline parallelism: decoder layers sharded into stages over a `pp`
mesh axis.

The reference cannot scale past `nNodes <= nKvHeads` — its only cross-node
strategy is tensor parallelism, bounded by the KV-head count (SURVEY.md §2
parallelism checklist; src/app.cpp:236-240). Pipeline stages lift that
ceiling: each stage holds a contiguous range of L/pp layers (weights AND
that range's KV cache), activations hop stage-to-stage over ICI
(`lax.ppermute` of one [B, T, D] tensor — the smallest inter-chip payload
in the whole model), and the per-stage HBM footprint shrinks by pp. A
70B+ checkpoint that cannot fit tp<=8 chips runs as pp stages of tp
groups.

Schedule (inference forward, single microbatch): P pipeline ticks; at
tick i stage i runs its local layer scan on the activation it received,
every other stage computes the same program on pass-through data and
discards it (SPMD requires identical programs; the discarded compute is
the classic pipeline bubble). Latency per forward is the same L layer
steps the single-device program pays — the bubble costs device
*utilization*, not request latency, so for fit-constrained serving the
trade is free. Stage-local math is `models.transformer.run_layers` —
bit-identical to the single-device path.

Caches: the [L, ...] KV cache shards its LAYER axis over pp (each stage
owns its range's cache); a stage's cache only commits on its active tick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..formats.model_file import LlmHeader


def validate_pp(h: LlmHeader, pp: int) -> None:
    if pp < 1 or (pp & (pp - 1)) != 0:
        raise ValueError(f"pp must be a power of two, got {pp}")
    if pp > 1 and h.n_layers % pp != 0:
        raise ValueError(
            f"nLayers={h.n_layers} not divisible by pp={pp} (stages hold "
            "equal layer ranges)"
        )


def pp_param_specs(base: dict) -> dict:
    """Layer-stacked params shard the stage axis on their leading (layer)
    dim; global tensors (embed, wcls, norms, rope) stay replicated on pp.
    `base` is parallel.sharding.param_spec_tree output (tp rules), whose
    layers-tree specs lead with the layer axis (None or empty = the
    replicated norms) — pp takes that axis over."""

    def with_pp(spec):
        tup = tuple(spec)
        if not tup:
            return P("pp")
        if tup[0] is not None:
            raise ValueError(
                f"layers leaf's leading (layer) axis is already sharded "
                f"({spec}); pp cannot take it over"
            )
        return P(*(("pp",) + tup[1:]))

    out = dict(base)
    out["layers"] = {k: with_pp(spec) for k, spec in base["layers"].items()}
    return out


def forward_pp(
    params,
    h: LlmHeader,
    tokens: jnp.ndarray,  # [B, T] int32
    pos: jnp.ndarray,  # scalar or [B]
    cache,  # {"k","v"}: [L, B, KH, S, hd], layer axis pp-sharded
    mesh,
    attn_window: int = 0,
    attn_park_threshold: int = 0,
    logits_mode: str = "all",
):
    """Pipeline-parallel forward: same contract as models.forward.

    Stage-local compute runs with mesh=None (plain kernels, no nested
    shard_map); tp/sp composition inside a stage is future work — the
    engine currently accepts pp with tp=sp=dp=1.
    """
    from jax import shard_map

    from ..models.transformer import (
        attn_positions,
        logits_head,
        rope_slices,
        run_layers,
    )

    pp = mesh.shape["pp"]
    t = tokens.shape[1]
    attn_pos = attn_positions(pos, attn_park_threshold, cache["k"].shape[3])

    layers = params["layers"]
    globals_ = {
        k: params[k]
        for k in ("embed", "wcls", "final_norm", "rope_cos", "rope_sin")
    }

    stage_spec = P("pp")  # prefix spec: leading (layer) axis of every leaf
    repl = P()

    def body(layers, k_c, v_c, globals_, tokens, pos, attn_pos):
        stage = lax.axis_index("pp")
        cos, sin = rope_slices(globals_, pos, t)
        x = globals_["embed"][tokens]  # [B, T, D]
        for tick in range(pp):
            x_out, k_new, v_new = run_layers(
                x, layers, k_c, v_c, h, pos, attn_pos, cos, sin,
                mesh=None, attn_window=attn_window,
            )
            active = stage == tick
            # commit this stage's cache range only on its active tick;
            # inactive ticks computed on pass-through data
            k_c = jnp.where(active, k_new, k_c)
            v_c = jnp.where(active, v_new, v_c)
            x = jnp.where(active, x_out, x)
            # hand the activation to the next stage; after the last tick
            # this rotates the final stage's result onto stage 0
            x = lax.ppermute(
                x, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
        # broadcast stage 0's (final) activation to all stages, then every
        # stage computes the replicated logits head
        x = lax.psum(jnp.where(stage == 0, x, jnp.zeros_like(x)), "pp")
        logits = logits_head(x, globals_, h, None, logits_mode)
        return logits, k_c, v_c

    logits, k_new, v_new = shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_spec, stage_spec, stage_spec, repl, repl, repl, repl),
        out_specs=(repl, stage_spec, stage_spec),
        check_vma=False,
    )(layers, cache["k"], cache["v"], globals_, tokens, pos, attn_pos)
    return logits, {"k": k_new, "v": v_new}
