"""lockwatch: runtime lock-order tracking and deterministic interleaving.

Static rules (``guarded-attrs``) catch *missing* locks; this module
catches *wrong lock orders* and makes thread races reproducible:

* :class:`LockWatch` + :class:`TrackedLock` — a drop-in
  ``threading.Lock`` that records the cross-thread acquisition-order
  graph (edge ``A -> B`` whenever ``B`` is taken while ``A`` is held)
  and raises :class:`LockOrderViolation` the moment an acquisition
  would close a cycle — turning a once-a-week deadlock hang into an
  immediate, stack-traced test failure.

* :func:`make_lock` / :func:`make_condition` — the factory production
  code calls at its lock sites. Plain ``threading`` primitives unless
  ``DLLAMA_LOCKWATCH=1`` (test mode), so the hot path pays nothing.

* :class:`Interleaver` — a seeded cooperative scheduler: spawned
  threads run ONE at a time and hand control back at explicit
  :meth:`Interleaver.step` points; which parked thread runs next is
  chosen by a seeded ``random.Random``. The same seed replays the same
  interleaving exactly, which is what lets the PR 6 match->adopt race
  live on as a deterministic regression test instead of a war story.

Threads under an Interleaver must never block outside a step point —
take locks with :meth:`Interleaver.acquire` (a non-blocking acquire
loop that yields to the scheduler between attempts) so a schedule that
*would* deadlock parks instead of hanging the test run.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """An acquisition would close a cycle in the lock-order graph."""


# -- acquisition-order graph -------------------------------------------------


class LockWatch:
    """Records which locks are taken while which others are held, across
    all threads, and refuses the edge that would create a cycle."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._held: Dict[int, List[str]] = {}  # thread ident -> lock stack
        self._edges: Dict[str, Set[str]] = {}  # A -> {B taken under A}
        self._edge_owner: Dict[Tuple[str, str], str] = {}  # edge -> thread

    def _find_path_locked(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst in the edge graph (DFS); caller holds
        ``self._mu``."""
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        seen: Set[str] = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in sorted(self._edges.get(node, ())):
                stack.append((nxt, path + [nxt]))
        return None

    def record_attempt(self, name: str) -> Optional[str]:
        """Record edges held -> ``name``; returns a human-readable cycle
        description if one of them would close a cycle (caller raises)."""
        ident = threading.get_ident()
        tname = threading.current_thread().name
        with self._mu:
            for h in self._held.get(ident, []):
                if h == name or name in self._edges.get(h, ()):
                    continue
                path = self._find_path_locked(name, h)
                if path is not None:
                    owner = self._edge_owner.get((path[0], path[1]), "?")
                    cyc = " -> ".join([h] + path)
                    return (
                        f"acquiring {name!r} while holding {h!r} "
                        f"(thread {tname!r}) closes the cycle {cyc}; the "
                        f"reverse order was first taken by thread {owner!r}"
                    )
                self._edges.setdefault(h, set()).add(name)
                self._edge_owner.setdefault((h, name), tname)
        return None

    def push(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            self._held.setdefault(ident, []).append(name)

    def pop(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            held = self._held.get(ident, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    def edges(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {a: set(bs) for a, bs in self._edges.items()}

    def held_by_current(self) -> List[str]:
        with self._mu:
            return list(self._held.get(threading.get_ident(), []))

    def reset(self) -> None:
        with self._mu:
            self._held.clear()
            self._edges.clear()
            self._edge_owner.clear()


class TrackedLock:
    """``threading.Lock`` plus lock-order bookkeeping via a LockWatch.

    Duck-types the Lock API (``acquire``/``release``/``locked``/context
    manager), so it also serves as the inner lock of a
    ``threading.Condition``.
    """

    def __init__(self, name: str, watch: LockWatch) -> None:
        self.name = name
        self._watch = watch
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # check BEFORE blocking: the schedule that would deadlock
            # raises here instead of hanging
            cyc = self._watch.record_attempt(self.name)
            if cyc is not None:
                raise LockOrderViolation(cyc)
            ok = self._inner.acquire(True, timeout)
        else:
            ok = self._inner.acquire(False)
            if ok:
                cyc = self._watch.record_attempt(self.name)
                if cyc is not None:
                    self._inner.release()
                    raise LockOrderViolation(cyc)
        if ok:
            self._watch.push(self.name)
        return ok

    def release(self) -> None:
        self._watch.pop(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TrackedLock({self.name!r}, locked={self.locked()})"


# -- env-gated factory (production lock sites call these) --------------------

_watch_init_mu = threading.Lock()
_global_watch: Optional[LockWatch] = None


def enabled() -> bool:
    return os.environ.get("DLLAMA_LOCKWATCH", "0") not in ("", "0")


def global_watch() -> LockWatch:
    global _global_watch
    with _watch_init_mu:
        if _global_watch is None:
            _global_watch = LockWatch()
        return _global_watch


def make_lock(name: str) -> "threading.Lock | TrackedLock":
    """A lock for a named production site: plain ``threading.Lock``
    normally, a :class:`TrackedLock` under ``DLLAMA_LOCKWATCH=1``."""
    if not enabled():
        return threading.Lock()
    return TrackedLock(name, global_watch())


def make_condition(name: str) -> threading.Condition:
    """Same gate for ``threading.Condition`` sites: in watch mode the
    condition's inner lock is tracked, so waiter re-acquisition shows up
    in the order graph too."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(TrackedLock(name, global_watch()))


# -- deterministic interleaving harness --------------------------------------


class _Abort(BaseException):
    """Internal: unwinds a controlled thread when the harness gives up."""


class Interleaver:
    """Seeded cooperative scheduler for race regression tests.

    ``spawn()`` registers named thread bodies; ``run()`` starts them and
    grants execution to exactly one at a time. A controlled thread runs
    until its next :meth:`step` call, where it parks and the scheduler
    picks the next runnable thread with a seeded RNG. The (name, label)
    sequence is recorded in ``trace`` — identical for identical seeds.
    """

    def __init__(self, seed: int = 0, timeout_s: float = 10.0) -> None:
        self.rng = random.Random(seed)
        self.timeout_s = timeout_s
        self.cv = threading.Condition()
        self._threads: Dict[str, threading.Thread] = {}
        self._names: Dict[int, str] = {}  # thread ident -> spawn name
        self.parked: Set[str] = set()
        self.finished: Set[str] = set()
        self.granted: Optional[str] = None
        self.trace: List[Tuple[str, str]] = []
        self.errors: List[Tuple[str, BaseException]] = []
        self._aborted = False

    # -- called from the harness (main) thread ---------------------------

    def spawn(self, name: str, fn: Callable[[], None]) -> None:
        if name in self._threads:
            raise ValueError(f"duplicate interleaver thread {name!r}")
        t = threading.Thread(  # dlint: disable=thread-hygiene — joined in run() below via self._threads
            target=self._body, args=(name, fn), daemon=True,
            name=f"dllama-itl-{name}",
        )
        self._threads[name] = t

    def run(self) -> List[Tuple[str, str]]:
        """Drive every spawned thread to completion; returns the trace.
        Re-raises the first exception a controlled thread died with
        (e.g. a LockOrderViolation)."""
        for t in self._threads.values():
            t.start()
        deadline = time.monotonic() + self.timeout_s
        with self.cv:
            while len(self.finished) < len(self._threads):
                if self.granted is None and self.parked:
                    pick = self.rng.choice(sorted(self.parked))
                    self.parked.discard(pick)
                    self.granted = pick
                    self.cv.notify_all()
                    continue
                if not self.cv.wait(timeout=0.2):
                    if time.monotonic() > deadline:
                        self._aborted = True
                        self.cv.notify_all()
                        raise RuntimeError(
                            f"interleaver stalled (a controlled thread is "
                            f"blocking outside a step point?): "
                            f"granted={self.granted!r} "
                            f"parked={sorted(self.parked)} "
                            f"finished={sorted(self.finished)}"
                        )
        for t in self._threads.values():
            t.join(timeout=2.0)
        with self.cv:
            if self.errors:
                raise self.errors[0][1]
            return list(self.trace)

    # -- called from controlled threads -----------------------------------

    def step(self, label: str = "") -> None:
        """Park here until the scheduler grants this thread the next run
        slice. No-op when the calling thread isn't harness-controlled, so
        shared code paths can be instrumented unconditionally."""
        name = self._names.get(threading.get_ident())
        if name is None:
            return
        with self.cv:
            self.trace.append((name, label))
            self.parked.add(name)
            if self.granted == name:
                self.granted = None
            self.cv.notify_all()
            while self.granted != name:
                if self._aborted:
                    raise _Abort()
                self.cv.wait(timeout=0.2)

    def acquire(self, lock: "threading.Lock | TrackedLock", label: str = "") -> "_Held":
        """Cooperatively take ``lock``: never blocks while holding the
        run slice, so a would-deadlock schedule parks (and times out
        with a diagnostic) instead of wedging the whole test run."""
        while not lock.acquire(blocking=False):
            self.step(f"acquire-wait:{label}")
        return _Held(lock)

    # -- internals ---------------------------------------------------------

    def _body(self, name: str, fn: Callable[[], None]) -> None:
        self._names[threading.get_ident()] = name
        try:
            self.step("start")
            fn()
        except _Abort:
            pass
        except BaseException as e:
            with self.cv:
                self.errors.append((name, e))
        finally:
            with self.cv:
                self.finished.add(name)
                self.parked.discard(name)
                if self.granted == name:
                    self.granted = None
                self.cv.notify_all()


class _Held:
    """Context manager returned by :meth:`Interleaver.acquire`."""

    def __init__(self, lock: "threading.Lock | TrackedLock") -> None:
        self._lock = lock

    def __enter__(self) -> "_Held":
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()
