"""dlint: project-native static analysis for the serving path.

PR 6's review caught two silent cross-request KV-corruption races only
by careful human reading — exactly the class of lock-discipline and
refcount-pairing bug a project-aware static pass flags mechanically.
This package is that pass: an AST lint framework with rules written
against THIS codebase's conventions (``self._lock`` guarding, the
``PagePool.retain``/``release`` ownership protocol, injectable clocks,
JAX trace purity, thread hygiene, the metrics↔docs contract).

One entrypoint runs everything::

    python -m dllama_tpu.analysis            # lint the repo, exit 0/1
    python -m dllama_tpu.analysis --list-rules
    python -m dllama_tpu.analysis --update-baseline
    python -m dllama_tpu.analysis --prune    # drop stale baseline entries
    python -m dllama_tpu.analysis --hlo      # lint COMPILED programs

Per-line suppressions use ``# dlint: disable=<rule>[,<rule>] — reason``
on the offending line; pre-existing findings can instead live in the
checked-in baseline (``dlint-baseline.json``), which CI treats as the
only findings allowed to exist. See docs/static_analysis.md.

The runtime half of the tooling — a test-mode lock wrapper that records
the cross-thread lock acquisition-order graph and fails on cycles, plus
a deterministic seeded interleaving harness — lives in
:mod:`dllama_tpu.analysis.lockwatch`.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    Finding,
    Repo,
    Rule,
    SourceModule,
    load_baseline,
    run_rules,
    write_baseline,
)


def all_rules() -> list:
    """Every registered rule, instantiated (import-cycle-free accessor:
    rule modules import core, never the other way around)."""
    from .rules_clock import DirectClockRule
    from .rules_dashboard import DashboardStaticRule
    from .rules_env import EnvKnobDocsRule
    from .rules_except import SilentExceptRule
    from .rules_kv import RetainReleaseRule
    from .rules_locks import GuardedAttrsRule
    from .rules_metrics import MetricsDocsRule
    from .rules_threads import ThreadHygieneRule
    from .rules_trace import TracePurityRule

    return [
        GuardedAttrsRule(),
        RetainReleaseRule(),
        DirectClockRule(),
        TracePurityRule(),
        ThreadHygieneRule(),
        MetricsDocsRule(),
        DashboardStaticRule(),
        EnvKnobDocsRule(),
        SilentExceptRule(),
    ]
