"""dashboard-static: the live dashboard must stay self-contained.

``GET /dashboard`` (obs/dashboard.py) promises a single-file page —
inline CSS, inline JS, canvas rendering — that works from
``curl -o dash.html`` on an air-gapped host and never phones home. One
``<script src=...cdn...>`` quietly added in review would break both
properties, so the contract is enforced here: any external reference
inside the module's string literals (the HTML template) is a finding.

Flagged inside string constants of ``obs/dashboard.py``:

* absolute URLs (``http://`` / ``https://``);
* scheme-relative references (``src="//..."`` / ``href="//..."``);
* ``<script src=...>`` and ``<link ... href=...>`` tags (inline-only);
* CSS ``@import``.

The scan walks AST string constants — not raw source lines — so code
comments may *mention* the forbidden patterns when documenting the rule.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .core import Finding, Rule, SourceModule

DASHBOARD_MODULES = ("dllama_tpu/obs/dashboard.py",)

_PATTERNS: tuple[tuple[re.Pattern, str], ...] = (
    (
        re.compile(r"https?://", re.I),
        "absolute URL in the dashboard template (must be self-contained)",
    ),
    (
        re.compile(r"""(?:src|href)\s*=\s*["']//""", re.I),
        "scheme-relative external reference in the dashboard template",
    ),
    (
        re.compile(r"<script\s[^>]*src", re.I),
        "<script src=...> in the dashboard template (scripts must be inline)",
    ),
    (
        re.compile(r"<link\s[^>]*href", re.I),
        "<link href=...> in the dashboard template (styles must be inline)",
    ),
    (
        re.compile(r"@import", re.I),
        "CSS @import in the dashboard template (styles must be inline)",
    ),
)


class DashboardStaticRule(Rule):
    name = "dashboard-static"
    description = (
        "the /dashboard page must be self-contained: no external URLs, "
        "script/style includes, or CSS imports in obs/dashboard.py"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        if mod.rel not in DASHBOARD_MODULES:
            return
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            ):
                continue
            for pattern, why in _PATTERNS:
                for m in pattern.finditer(node.value):
                    # anchor the finding to the line inside the (multi-
                    # line) template literal where the match sits
                    line = node.lineno + node.value[: m.start()].count("\n")
                    yield mod.finding(
                        self.name, line, f"{why}: {m.group(0)!r}"
                    )
