"""env-knob-docs: every ``DLLAMA_*`` environment knob the code reads
must be documented somewhere an operator will find it.

Same two-sided sync contract as the metrics rule
(:mod:`.rules_metrics`), applied to configuration instead of
telemetry: a knob read in code but absent from README.md and docs/ is
behavior nobody can discover; a knob documented but read nowhere is an
operator setting a dead variable.

Read sites recognized (regex over whole file text, since helper calls
wrap across lines): ``os.environ.get/os.getenv/os.environ[...]`` and
the project's ``_env_int/_env_float/_env_str/_env_bool`` helpers, each
with a literal ``"DLLAMA_..."`` name. ``environ.setdefault`` is a
write, not a read, and names in docstrings/comments have no read site
— neither counts. Doc side: any ``DLLAMA_*`` token in README.md or any
``docs/*.md``; a trailing-star family mention (``DLLAMA_WATCHDOG_*``)
documents every knob sharing the prefix.
"""

from __future__ import annotations

import re
from typing import Iterable

from .core import Finding, Repo, Rule

_READ_SITE = re.compile(
    r"(?:environ\.get|\bgetenv|environ\[|_env_int|_env_float|_env_str"
    r"|_env_bool)\s*\(?\s*[\"'](DLLAMA_[A-Z0-9_]+)[\"']"
)
_DOC_NAME = re.compile(r"\b(DLLAMA_[A-Z0-9_]+)(\*)?")


def read_knobs(repo: Repo) -> dict[str, tuple[str, int]]:
    """knob name -> (path, line) of its first read site."""
    knobs: dict[str, tuple[str, int]] = {}
    for mod in repo.modules:
        for m in _READ_SITE.finditer(mod.text):
            line = mod.text.count("\n", 0, m.start()) + 1
            knobs.setdefault(m.group(1), (mod.rel, line))
    return knobs


def documented_knobs(
    repo: Repo,
) -> tuple[dict[str, tuple[str, int]], dict[str, tuple[str, int]]]:
    """(exact knob mentions, family-prefix mentions) across README.md
    and docs/*.md, each name -> (doc path, line) of its first mention.
    A ``DLLAMA_FOO_*`` token lands in the prefix dict as ``DLLAMA_FOO_``."""
    exact: dict[str, tuple[str, int]] = {}
    prefixes: dict[str, tuple[str, int]] = {}
    docs = [repo.root / "README.md"]
    docs_dir = repo.root / "docs"
    if docs_dir.is_dir():
        docs.extend(sorted(docs_dir.glob("*.md")))
    for doc in docs:
        if not doc.exists():
            continue
        text = doc.read_text()
        rel = doc.relative_to(repo.root).as_posix()
        for m in _DOC_NAME.finditer(text):
            loc = (rel, text.count("\n", 0, m.start()) + 1)
            if m.group(2):
                prefixes.setdefault(m.group(1), loc)
            else:
                exact.setdefault(m.group(1), loc)
    return exact, prefixes


class EnvKnobDocsRule(Rule):
    name = "env-knob-docs"
    description = (
        "every DLLAMA_* env knob read in code is documented in README.md "
        "or docs/, and vice versa"
    )

    def check_repo(self, repo: Repo) -> Iterable[Finding]:
        code = read_knobs(repo)
        exact, prefixes = documented_knobs(repo)

        def covered(name: str) -> bool:
            return name in exact or any(
                name.startswith(p) for p in prefixes
            )

        for name in sorted(n for n in code if not covered(n)):
            path, line = code[name]
            yield Finding(
                rule=self.name, path=path, line=line,
                message=(
                    f"env knob {name} is read here but documented in "
                    f"neither README.md nor docs/"
                ),
            )
        for name in sorted(set(exact) - set(code)):
            path, line = exact[name]
            yield Finding(
                rule=self.name, path=path, line=line,
                message=(
                    f"env knob {name} is documented but read nowhere "
                    f"(operators would set a dead variable)"
                ),
            )
        for pref in sorted(
            p for p in prefixes
            if not any(n.startswith(p) for n in code)
        ):
            path, line = prefixes[pref]
            yield Finding(
                rule=self.name, path=path, line=line,
                message=(
                    f"env knob family {pref}* is documented but no knob "
                    f"with that prefix is read anywhere"
                ),
            )
