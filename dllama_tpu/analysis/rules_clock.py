"""direct-clock: clock-injectable modules must use the injected clock.

obs/watchdog.py, obs/slo.py and obs/spans.py accept a ``clock=``
parameter precisely so fake-clock tests can drive their stall rules and
sliding windows deterministically. A direct ``time.time()`` /
``time.monotonic()`` / ``time.perf_counter()`` call in such a module is
a hole in that determinism: the code path silently reads the real clock
and the fake-clock test can never cover it.

The rule fires in any module where some function signature has a
``clock`` or ``wall_clock`` parameter, on every *call* of a ``time``
module clock. A bare reference (``clock=time.monotonic`` as a default —
the injection point itself) is not a call and never fires.

Wall-clock timestamps for human-facing output are still legitimate —
inject them too (``wall_clock=time.time``) or suppress with
``# dlint: disable=direct-clock — why``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Finding, Rule, SourceModule

CLOCK_PARAMS = {"clock", "wall_clock"}
CLOCK_CALLS = {"time", "monotonic", "perf_counter", "monotonic_ns",
               "perf_counter_ns", "time_ns"}


class DirectClockRule(Rule):
    name = "direct-clock"
    description = (
        "modules with an injectable clock= parameter must not call "
        "time.time()/time.monotonic()/time.perf_counter() directly"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        if not self._is_clock_injectable(mod.tree):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in CLOCK_CALLS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
            ):
                yield mod.finding(
                    self.name,
                    node,
                    f"direct time.{fn.attr}() call in a clock-injectable "
                    f"module; route it through the injected clock so "
                    f"fake-clock tests cover this path",
                )

    def _is_clock_injectable(self, tree: ast.AST) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                names = [
                    a.arg
                    for a in (
                        args.posonlyargs + args.args + args.kwonlyargs
                    )
                ]
                if CLOCK_PARAMS & set(names):
                    return True
        return False
