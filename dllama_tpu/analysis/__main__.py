"""``python -m dllama_tpu.analysis`` — run every dlint rule on the repo.

``--hlo`` switches from source lint to compiled-program lint
(:mod:`.xlalint`): it builds a tiny CPU engine, pre-compiles the
admission program set, and checks every executable's HLO against the
donation/collective/dtype/host/cost policies, gated by
``xlalint-baseline.json``. ``--prune`` (in either mode) rewrites the
baseline minus entries that no longer match any finding, so dead
suppressions can't accumulate.

Exit 0 when every finding is fixed, inline-suppressed, or baselined;
exit 1 on any new finding (what CI's fast lane gates on); exit 2 on
usage errors or unparseable sources.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from . import all_rules
from .core import (
    BASELINE_NAME,
    apply_baseline,
    collect_repo,
    load_baseline,
    run_rules,
    write_baseline,
)
from .xlalint import XLALINT_BASELINE_NAME


def repo_root() -> pathlib.Path:
    # analysis/ -> dllama_tpu/ -> repo root
    return pathlib.Path(__file__).resolve().parent.parent.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dllama_tpu.analysis",
        description="project-native static analysis (dlint)",
    )
    ap.add_argument(
        "targets", nargs="*",
        help="files/directories to lint (default: dllama_tpu/, bench.py, "
             "launch.py, scripts/)",
    )
    ap.add_argument(
        "--rules", default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <repo>/{BASELINE_NAME})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings and exit 0",
    )
    ap.add_argument(
        "--prune", action="store_true",
        help="rewrite the baseline minus stale entries (ones matching no "
             "current finding) and exit 0",
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="lint COMPILED programs (xlalint): build a tiny CPU engine, "
             "precompile the admission program set, check HLO policies "
             f"against <repo>/{XLALINT_BASELINE_NAME}",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.hlo:
        if args.list_rules:
            from .xlalint import all_hlo_rules

            for hr in all_hlo_rules():
                print(f"{hr.name:24s} {hr.description}")
            return 0
        from .xlalint import run_hlo_cli

        return run_hlo_cli(args)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:16s} {r.description}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = repo_root()
    repo = collect_repo(root, args.targets or None)
    if repo.parse_errors:
        for rel, err in repo.parse_errors:
            print(f"{rel}: PARSE ERROR: {err}", file=sys.stderr)
        return 2

    findings, n_suppressed = run_rules(repo, rules)

    baseline_path = (
        pathlib.Path(args.baseline) if args.baseline
        else root / BASELINE_NAME
    )
    if args.update_baseline:
        write_baseline(baseline_path, findings)
        print(
            f"baseline written: {len(findings)} finding(s) -> "
            f"{baseline_path}"
        )
        return 0

    baseline = set() if args.no_baseline else load_baseline(baseline_path)
    new, baselined, stale = apply_baseline(findings, baseline)

    if args.prune:
        # keep exactly the entries that still match a finding: stale
        # fingerprints (rule/file fixed or renamed) drop out, new
        # findings are NOT added — pruning never widens the baseline
        write_baseline(baseline_path, baselined)
        print(
            f"baseline pruned: {len(stale)} stale entr"
            f"{'y' if len(stale) == 1 else 'ies'} removed, "
            f"{len(baselined)} kept -> {baseline_path}"
        )
        return 0

    for f in new:
        print(f.render())
    if not args.quiet:
        if stale:
            print(
                f"note: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} no longer match any "
                f"finding — prune with --prune"
            )
        print(
            f"dlint: {len(repo.modules)} files, {len(rules)} rules, "
            f"{len(new)} new finding(s), {len(baselined)} baselined, "
            f"{n_suppressed} suppressed inline"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
