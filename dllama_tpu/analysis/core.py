"""dlint core: findings, rule base class, suppressions, baseline, runner.

A rule sees the repo through two hooks:

* ``check_module(mod)`` — once per parsed source file (most rules);
* ``check_repo(repo)``  — once per run, for cross-file contracts (the
  metrics↔docs rule).

Suppression model (two layers, both visible in review):

* **inline** — ``# dlint: disable=rule-a,rule-b — why this is fine`` on
  the finding's line silences those rules for that line only. The
  justification text is free-form but the convention (enforced by
  review, not the tool) is one line of WHY.
* **baseline** — ``dlint-baseline.json`` at the repo root lists finding
  fingerprints that predate the rule and are allowed to persist.
  Fingerprints deliberately exclude line numbers so unrelated edits
  don't churn the file; ``--update-baseline`` rewrites it.

Exit codes: 0 clean (or fully baselined/suppressed), 1 findings, 2
usage/internal error.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator

BASELINE_NAME = "dlint-baseline.json"

# ``# dlint: disable=rule-a,rule-b`` optionally followed by free text
_SUPPRESS = re.compile(r"#\s*dlint:\s*disable=([a-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int  # 1-indexed
    message: str

    def fingerprint(self) -> str:
        # no line number: survives unrelated edits above the finding
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Rule:
    """Base class; subclasses set ``name``/``description`` and override
    one or both check hooks."""

    name = ""
    description = ""

    def check_module(self, mod: "SourceModule") -> Iterable[Finding]:
        return ()

    def check_repo(self, repo: "Repo") -> Iterable[Finding]:
        return ()


class SourceModule:
    """One parsed source file plus its per-line suppression table."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path) -> None:
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        # line -> set of suppressed rule names
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressions.setdefault(i, set()).update(rules)

    def suppressed(self, finding: Finding) -> bool:
        return finding.rule in self.suppressions.get(finding.line, ())

    def finding(self, rule: str, node: ast.AST | int, message: str) -> Finding:
        line = node if isinstance(node, int) else getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.rel, line=line, message=message)


@dataclass
class Repo:
    root: pathlib.Path
    modules: list[SourceModule] = field(default_factory=list)
    # files that exist but failed to parse: reported, never silently skipped
    parse_errors: list[tuple[str, str]] = field(default_factory=list)

    def module(self, rel: str) -> SourceModule | None:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None


DEFAULT_TARGETS = ("dllama_tpu", "bench.py", "launch.py", "scripts")
_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


def collect_repo(
    root: pathlib.Path, targets: Iterable[str] | None = None
) -> Repo:
    repo = Repo(root=root)
    paths: list[pathlib.Path] = []
    for t in targets or DEFAULT_TARGETS:
        p = root / t
        if p.is_dir():
            paths.extend(
                q
                for q in sorted(p.rglob("*.py"))
                if not (_SKIP_DIRS & set(q.parts))
            )
        elif p.is_file():
            paths.append(p)
    for p in paths:
        try:
            repo.modules.append(SourceModule(root, p))
        except SyntaxError as e:
            repo.parse_errors.append((p.relative_to(root).as_posix(), str(e)))
    return repo


def run_rules(
    repo: Repo, rules: Iterable[Rule]
) -> tuple[list[Finding], int]:
    """All unsuppressed findings plus the count of inline-suppressed
    ones (surfaced in the summary so suppressions stay visible)."""
    findings: list[Finding] = []
    n_suppressed = 0
    by_rel = {m.rel: m for m in repo.modules}
    for rule in rules:
        for mod in repo.modules:
            for f in rule.check_module(mod):
                if mod.suppressed(f):
                    n_suppressed += 1
                else:
                    findings.append(f)
        for f in rule.check_repo(repo):
            mod = by_rel.get(f.path)
            if mod is not None and mod.suppressed(f):
                n_suppressed += 1
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, n_suppressed


# -- baseline ---------------------------------------------------------------

def load_baseline(path: pathlib.Path) -> set[str]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return set(data.get("findings", []))


def write_baseline(path: pathlib.Path, findings: Iterable[Finding]) -> None:
    data = {
        "comment": (
            "dlint baseline: fingerprints of pre-existing findings allowed "
            "to persist. Regenerate with "
            "`python -m dllama_tpu.analysis --update-baseline`; shrink it "
            "whenever you fix one."
        ),
        "findings": sorted({f.fingerprint() for f in findings}),
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def apply_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], list[Finding], set[str]]:
    """Split into (new, baselined) and report stale baseline entries."""
    new: list[Finding] = []
    old: list[Finding] = []
    seen: set[str] = set()
    for f in findings:
        fp = f.fingerprint()
        if fp in baseline:
            old.append(f)
            seen.add(fp)
        else:
            new.append(f)
    stale = baseline - seen
    return new, old, stale


# -- shared AST helpers (used by several rules) -----------------------------

def is_self_attr(node: ast.AST, name: str | None = None) -> bool:
    """``self.X`` (optionally a specific X)."""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (name is None or node.attr == name)
    )


def dotted(node: ast.AST) -> str:
    """Best-effort dotted-name rendering of an expression (for keys and
    messages); falls back to ast.unparse for anything unusual."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted(node.value)}.{node.attr}"
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse handles all exprs today
        return "<expr>"


def iter_methods(cls: ast.ClassDef) -> Iterator[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
