"""The compiled-program (HLO) rules behind xlalint.

Each rule sees one :class:`~.xlalint.HloProgram` — optimized HLO text +
XLA cost analysis + the engine-derived :class:`~.xlalint.FamilyPolicy`
— and yields :class:`~.xlalint.HloFinding`s whose messages are
deliberately line-free and value-free (raw numbers ride in the
``detail`` field) so baseline fingerprints survive backend and version
churn. The text parsers at the top are shared with
``tests/test_parallel.py``'s sharding census tests, which used to carry
their own one-off regexes.

What the parsers rely on (validated against the optimized HLO jax
emits on CPU and TPU):

* ops appear as ``%name = TYPE[dims]{layout} op-name(...)`` one per
  line; async collectives split into ``op-start``/``op-done`` pairs
  (normalized to the base op here, and ``-done`` lines skipped so one
  async collective is counted once);
* donation shows up on the ``HloModule`` header line as
  ``input_output_alias={ {0}: (13, {}, may-alias), ... }`` with one
  ``{output_index}: (...)`` entry per donated leaf;
* host callbacks (``jax.pure_callback`` & co.) lower to custom-calls
  whose target names a callback/host transfer — while Pallas kernels
  are custom-calls too (``tpu_custom_call``), so host detection matches
  a denylist of target substrings, never "any custom-call".
"""

from __future__ import annotations

import re
from typing import Iterable, Iterator

from .xlalint import FamilyPolicy, HloFinding, HloProgram, HloRule

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "collective-broadcast",
    "all-to-all",
)

# %name = <result types...> op-name(   — with optional async suffix.
# The result segment (group 1) is everything between "=" and the op
# token; it may be a bare shape or a tuple of shapes for -start forms.
_COLLECTIVE_RE = re.compile(
    r"=\s*([^=]*?)\s*\b("
    + "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    + r")(-start|-done)?\("
)

_HOST_OP_RE = re.compile(
    r"=\s*[^=]*?\s*\b(infeed|outfeed|send|recv)(-done)?\("
)

_SHAPE_RE = re.compile(r"\b(pred|[a-z]+[0-9]+(?:e[0-9]+m[0-9]+\w*)?)\[([0-9,]*)\]")

_CUSTOM_CALL_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')

_ALIAS_ENTRY_RE = re.compile(r"\{[0-9, ]*\}\s*:\s*\(")

#: custom-call target substrings that mean "leaves the device for the
#: host". Pallas ("tpu_custom_call") and cuDNN/oneDNN math targets
#: deliberately do NOT match.
HOST_TARGET_MARKERS = ("callback", "infeed", "outfeed", "host")

_DTYPE_BITS = {
    "pred": 8, "s4": 4, "u4": 4, "s8": 8, "u8": 8, "s16": 16, "u16": 16,
    "s32": 32, "u32": 32, "s64": 64, "u64": 64,
    "f16": 16, "bf16": 16, "f32": 32, "f64": 64,
}


def dtype_bits(dtype: str) -> int:
    """Storage bits of an HLO element type name (f8E4M3 variants parse
    as 8; unknown names report 0 = never over any limit)."""
    if dtype in _DTYPE_BITS:
        return _DTYPE_BITS[dtype]
    m = re.match(r"[a-z]+(\d+)", dtype)
    return int(m.group(1)) if m else 0


def strip_strings(txt: str) -> str:
    """HLO text with every quoted string blanked, so op scans never
    match inside metadata/backend_config payloads."""
    return re.sub(r'"[^"]*"', '""', txt)


def parse_shapes(segment: str) -> list[tuple[str, tuple[int, ...]]]:
    """Every ``dtype[d0,d1,...]`` in a result segment as
    (dtype, dims) — scalars parse as empty dims."""
    out: list[tuple[str, tuple[int, ...]]] = []
    for m in _SHAPE_RE.finditer(segment):
        dims = tuple(int(d) for d in m.group(2).split(",") if d.strip())
        out.append((m.group(1), dims))
    return out


def iter_collectives(
    hlo_text: str,
) -> Iterator[tuple[str, list[tuple[str, tuple[int, ...]]]]]:
    """(base op name, result shapes) for every collective in a program.
    Async pairs count once: ``-done`` lines are skipped and the
    ``-start`` line's operand-side shapes already include the result."""
    for line in strip_strings(hlo_text).splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group(3) == "-done":
            continue
        yield m.group(2), parse_shapes(m.group(1))


def collective_census(hlo_text: str) -> dict:
    """op -> count over a whole program (the census the sharding tests
    assert on)."""
    census: dict = {}
    for op, _ in iter_collectives(hlo_text):
        census[op] = census.get(op, 0) + 1
    return census


def gather_result_shapes(hlo_text: str) -> list[tuple[str, tuple[int, ...]]]:
    """Result shapes of every all-gather (async ones via their -start
    line; the true gathered result is the largest shape on it)."""
    shapes: list[tuple[str, tuple[int, ...]]] = []
    for op, res in iter_collectives(hlo_text):
        if op == "all-gather" and res:
            shapes.append(max(res, key=lambda s: _elems(s[1])))
    return shapes


def _elems(dims: tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def scatter_result_dims(hlo_text: str) -> list[tuple[int, ...]]:
    """Result dims of every scatter op (the sharding tests pin the KV
    cyclic write to SHARD-LOCAL scatters: rows = S/sp, never full S)."""
    out: list[tuple[int, ...]] = []
    for line in strip_strings(hlo_text).splitlines():
        m = re.search(
            r"=\s*[a-z]+[0-9]+\[([0-9,]+)\][^=]*?\bscatter\(", line
        )
        if m:
            out.append(tuple(int(d) for d in m.group(1).split(",")))
    return out


def forbidden_gather_findings(
    hlo_text: str, table_dims: Iterable[tuple[int, ...]]
) -> list[tuple[str, tuple[int, ...]]]:
    """All-gather results whose trailing-two dims match a full-table
    shape — (dtype, dims) per offender. The callable core of the
    collective-census rule's regather check, shared with
    tests/test_parallel.py's embed/wcls census test."""
    tables = {tuple(t) for t in table_dims}
    hits: list[tuple[str, tuple[int, ...]]] = []
    for dtype, dims in gather_result_shapes(hlo_text):
        tail = dims[-2:] if len(dims) >= 2 else dims
        if tail in tables:
            hits.append((dtype, dims))
    return hits


def custom_call_targets(hlo_text: str) -> list[str]:
    """Every custom_call_target in a program (raw text: targets live
    inside the quoted strings strip_strings would blank)."""
    return _CUSTOM_CALL_TARGET_RE.findall(hlo_text)


def input_output_alias_count(hlo_text: str) -> int:
    """Number of donated-buffer aliases the executable honors, parsed
    from the module header's ``input_output_alias={...}`` map (balanced
    braces; 0 when the attribute is absent = every donation dropped)."""
    idx = hlo_text.find("input_output_alias={")
    if idx < 0:
        return 0
    start = idx + len("input_output_alias=")
    depth = 0
    end = start
    for i in range(start, len(hlo_text)):
        c = hlo_text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                end = i + 1
                break
    body = hlo_text[start:end]
    return len(_ALIAS_ENTRY_RE.findall(body))


def host_op_lines(hlo_text: str) -> list[str]:
    """infeed/outfeed/send/recv op names present in a program."""
    ops = []
    for line in strip_strings(hlo_text).splitlines():
        m = _HOST_OP_RE.search(line)
        if m and not m.group(2):  # count start of each pair once
            ops.append(m.group(1))
    return ops


def _name_dtypes(hlo_text: str) -> dict:
    """%name -> result element type for every instruction (the operand
    dtype table the upcast check walks)."""
    out: dict = {}
    for line in strip_strings(hlo_text).splitlines():
        m = re.match(r"\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*([a-z]+[0-9]+|pred)\[", line)
        if m:
            out[m.group(1)] = m.group(2)
    return out


def f32_upcast_store_dots(hlo_text: str) -> list[str]:
    """Names of dots that STORE f32 while fed from a 16-bit float path
    — either ``dot(bf16, bf16) -> f32`` directly or through a
    ``convert`` — the silent accumulate-and-store upcast xlalint's
    dtype policy forbids on bf16 engines. (An f32-ACCUMULATING dot that
    stores bf16, or converts its result back down, is fine and does not
    match.)"""
    stripped = strip_strings(hlo_text)
    dtypes = _name_dtypes(hlo_text)
    # dot results consumed by a convert back down to 16-bit float are
    # accumulator-only: XLA itself lowers dot(bf16, bf16) -> bf16 as
    # convert-up / f32 dot / convert-down, and that round-trip is fine
    downcast = {
        m.group(2)
        for m in re.finditer(
            r"=\s*(bf16|f16)\[[^\]]*\][^=]*?\bconvert\(\s*"
            r"(?:[a-z0-9]+\[[^\]]*\]\S*\s+)?%?([\w.\-]+)",
            stripped,
        )
    }
    hits: list[str] = []
    for line in stripped.splitlines():
        m = re.match(
            r"\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*f32\[[^\]]*\][^=]*?"
            r"\bdot\(([^)]*)\)",
            line,
        )
        if not m:
            continue
        if m.group(1) in downcast:
            continue
        operand_txt = m.group(2)
        # typed operand dumps show the 16-bit source inline
        if re.search(r"\b(?:bf16|f16)\[", operand_txt):
            hits.append(m.group(1))
            continue
        # otherwise resolve operand names through the instruction table
        names = re.findall(r"%([\w.\-]+)", operand_txt)
        if not names:
            names = [
                seg.strip().split()[-1]
                for seg in operand_txt.split(",")
                if seg.strip()
            ]
        for op_name in names:
            if dtypes.get(op_name) in ("bf16", "f16"):
                hits.append(m.group(1))
                break
            if op_name.startswith("convert"):
                src = _convert_source_dtype(hlo_text, op_name, dtypes)
                if src in ("bf16", "f16"):
                    hits.append(m.group(1))
                    break
    return hits


def _convert_source_dtype(
    hlo_text: str, convert_name: str, dtypes: dict[str, str]
) -> str | None:
    """Element type feeding a convert — from the operand's inline typed
    dump (``convert(bf16[...] %p1)``) or, for a bare operand name
    (``convert(%p1)``), resolved through the instruction table."""
    m = re.search(
        r"%?" + re.escape(convert_name)
        + r"\s*=\s*[a-z0-9]+\[[^\]]*\][^=]*?\bconvert\(\s*([^)]*)\)",
        strip_strings(hlo_text),
    )
    if not m:
        return None
    operand = m.group(1).strip()
    typed = re.match(r"(pred|[a-z]+[0-9]+(?:e[0-9]+m[0-9]+\w*)?)\[", operand)
    if typed:
        return typed.group(1)
    name = re.match(r"%?([\w.\-]+)", operand)
    return dtypes.get(name.group(1)) if name else None


def dot_store_dtypes(hlo_text: str) -> list[str]:
    """Result element type of every dot in a program."""
    out: list[str] = []
    for line in strip_strings(hlo_text).splitlines():
        m = re.match(
            r"\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*([a-z]+[0-9]+)\[[^\]]*\][^=]*?\bdot\(",
            line,
        )
        if m:
            out.append(m.group(1))
    return out


# -- rules ------------------------------------------------------------------

class CollectiveCensusRule(HloRule):
    """Only the family's allowed collectives, no oversized all-gather,
    and no all-gather that reassembles a full weight/embed table."""

    name = "hlo-collective-census"
    description = (
        "compiled programs lower only their family's allowed collectives; "
        "all-gathers stay under the policy size cap and never rebuild a "
        "full sharded table"
    )

    def check(self, prog: HloProgram) -> Iterable[HloFinding]:
        pol = prog.policy
        seen_disallowed: set = set()
        for op, _ in iter_collectives(prog.hlo_text):
            if op not in pol.allowed_collectives and op not in seen_disallowed:
                seen_disallowed.add(op)
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"collective '{op}' not allowed in "
                    f"{prog.family} programs",
                )
        table_hits = {
            dims
            for _, dims in forbidden_gather_findings(
                prog.hlo_text, pol.forbidden_gather_dims
            )
        }
        seen_shapes: set = set()
        for dtype, dims in gather_result_shapes(prog.hlo_text):
            if dims in seen_shapes:
                continue
            seen_shapes.add(dims)
            if dims in table_hits:
                tail = dims[-2:] if len(dims) >= 2 else dims
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"all-gather reassembles a full sharded table "
                    f"{'x'.join(map(str, tail))}",
                    detail=f"result {dtype}[{','.join(map(str, dims))}]",
                )
            elif (
                pol.max_allgather_elements
                and _elems(dims) > pol.max_allgather_elements
            ):
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"all-gather result "
                    f"{dtype}[{','.join(map(str, dims))}] exceeds the "
                    f"family size cap",
                    detail=f"{_elems(dims)} > {pol.max_allgather_elements} "
                    f"elements",
                )


class DonationRule(HloRule):
    """Every donated buffer must appear in the executable's
    input_output_alias map — a dropped donation is silent double-HBM
    for the KV cache / page pool."""

    name = "hlo-donation"
    description = (
        "donate_argnums buffers appear as input_output_alias entries in "
        "the compiled executable"
    )

    def check(self, prog: HloProgram) -> Iterable[HloFinding]:
        if prog.expected_aliases <= 0:
            return
        got = input_output_alias_count(prog.hlo_text)
        if got < prog.expected_aliases:
            yield HloFinding(
                rule=self.name,
                path=prog.path,
                line=1,
                message=f"donation dropped: "
                f"{prog.expected_aliases - got} of "
                f"{prog.expected_aliases} donated buffers have no "
                f"input-output alias",
                detail=f"alias map has {got} entries",
            )


class HostRoundTripRule(HloRule):
    """Hot-path programs never leave the device: no host-callback
    custom-calls, no infeed/outfeed/send/recv, no f64 (which usually
    means host-side Python float math leaked into a trace)."""

    name = "hlo-host"
    description = (
        "no host callbacks, infeed/outfeed, send/recv, or f64 in "
        "hot-path compiled programs"
    )

    def check(self, prog: HloProgram) -> Iterable[HloFinding]:
        if not prog.policy.forbid_host:
            return
        seen: set = set()
        for target in custom_call_targets(prog.hlo_text):
            low = target.lower()
            if target not in seen and any(
                marker in low for marker in HOST_TARGET_MARKERS
            ):
                seen.add(target)
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"host-transfer custom-call '{target}'",
                )
        for op in sorted(set(host_op_lines(prog.hlo_text))):
            yield HloFinding(
                rule=self.name,
                path=prog.path,
                line=1,
                message=f"host-transfer op '{op}'",
            )
        if prog.policy.forbid_f64 and "f64[" in strip_strings(prog.hlo_text):
            yield HloFinding(
                rule=self.name,
                path=prog.path,
                line=1,
                message="f64 tensor in a hot-path program",
            )


class DtypePolicyRule(HloRule):
    """Weight-path dots store at most the policy width, and a bf16
    engine's dots never silently upcast to f32 accumulate-AND-store."""

    name = "hlo-dtype"
    description = (
        "dot-generals store within the family dtype width and never "
        "silently upcast a 16-bit float path to an f32 store"
    )

    def check(self, prog: HloProgram) -> Iterable[HloFinding]:
        pol = prog.policy
        if pol.max_dot_store_bits:
            over = sorted(
                {
                    d
                    for d in dot_store_dtypes(prog.hlo_text)
                    if dtype_bits(d) > pol.max_dot_store_bits
                }
            )
            for d in over:
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"dot stores {d}, wider than the "
                    f"{pol.max_dot_store_bits}-bit family limit",
                )
        if pol.forbid_f32_upcast_store:
            hits = f32_upcast_store_dots(prog.hlo_text)
            if hits:
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message="16-bit float path upcast to an f32 "
                    "dot store (accumulate-and-store)",
                    detail=f"dots: {', '.join(sorted(set(hits))[:4])}",
                )


class CostBudgetRule(HloRule):
    """XLA's own cost analysis stays under the roofline-derived ceiling
    for the program family — the regather/replication cliff guard."""

    name = "hlo-cost-budget"
    description = (
        "per-program bytes_accessed/flops stay under the roofline-"
        "derived family budget (obs.cost.program_cost_ceilings)"
    )

    def check(self, prog: HloProgram) -> Iterable[HloFinding]:
        if prog.cost is None:
            return
        checks = (
            ("bytes_accessed", prog.bytes_budget),
            ("flops", prog.flops_budget),
        )
        for metric, budget in checks:
            value = prog.cost.get(metric, 0.0)
            if budget > 0 and value > budget:
                yield HloFinding(
                    rule=self.name,
                    path=prog.path,
                    line=1,
                    message=f"{metric} exceeds the {prog.family} "
                    f"roofline budget",
                    detail=f"{value:.3e} > {budget:.3e}",
                )


__all__ = [
    "COLLECTIVE_OPS",
    "HOST_TARGET_MARKERS",
    "CollectiveCensusRule",
    "CostBudgetRule",
    "DonationRule",
    "DtypePolicyRule",
    "FamilyPolicy",
    "HostRoundTripRule",
    "collective_census",
    "custom_call_targets",
    "dot_store_dtypes",
    "dtype_bits",
    "f32_upcast_store_dots",
    "forbidden_gather_findings",
    "gather_result_shapes",
    "scatter_result_dims",
    "host_op_lines",
    "input_output_alias_count",
    "iter_collectives",
    "parse_shapes",
    "strip_strings",
]
