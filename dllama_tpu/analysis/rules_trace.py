"""trace-purity: no host side effects inside JAX-traced functions.

Anything executed under ``jax.jit`` / ``shard_map`` / Pallas tracing
runs ONCE, at trace time — a ``time.monotonic()`` read, a metrics
``.inc()``, a lock acquisition or a ``self.X = ...`` mutation inside a
traced function is silently burned into the compiled program: it fires
at compile, never per step, and usually "works" until someone trusts
the number. The engine's decode block compiles on a background prefetch
thread, so a lock taken at trace time can even deadlock against the
dispatch path.

Traced functions are found three ways, then closed transitively over
same-module calls:

* decorated with ``@jax.jit`` / ``@partial(jax.jit, ...)`` /
  ``@functools.partial(jax.jit, ...)``;
* passed to ``jax.jit(...)``, ``pl.pallas_call(...)``,
  ``shard_map(...)`` / ``shard_map_compat(...)`` (bare name or wrapped
  in ``partial``);
* called by name from an already-traced function in the same module.

Flagged inside a traced body: ``time.*`` clock calls, metric/recorder
side effects (``.inc``/``.observe``/``.labels``/``.record``,
``get_registry``/``get_recorder``/``get_span_tracker``), lock
acquisition (``with self._lock`` or any ``threading.*`` use),
``print``, ``logging``/``logger`` calls, ``os.environ`` reads, and
``self.X = ...`` host-state mutation. ``jax.debug.print`` /
``pl.debug_print`` are the sanctioned in-trace debug tools and are not
flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Finding, Rule, SourceModule, dotted

TRACER_TAILS = {"jit", "pallas_call", "shard_map", "shard_map_compat"}
METRIC_METHODS = {"inc", "observe", "labels", "record"}
OBS_GETTERS = {"get_registry", "get_recorder", "get_span_tracker"}


def _mentions_jit(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "jit":
            if isinstance(n.value, ast.Name) and n.value.id == "jax":
                return True
        if isinstance(n, ast.Name) and n.id == "pallas_call":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "pallas_call":
            return True
    return False


def _fn_names_from_arg(arg: ast.AST) -> list[str]:
    """Function names a tracer call-site argument refers to: a bare name
    or one wrapped in functools.partial(name, ...)."""
    if isinstance(arg, ast.Name):
        return [arg.id]
    if isinstance(arg, ast.Call):
        fn = arg.func
        tail = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else ""
        )
        if tail == "partial" and arg.args:
            return _fn_names_from_arg(arg.args[0])
    return []


class TracePurityRule(Rule):
    name = "trace-purity"
    description = (
        "functions reaching jax.jit/shard_map/pallas must not touch "
        "locks, metrics, time.*, or host-side state"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        index: dict[str, list] = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index.setdefault(n.name, []).append(n)

        traced: dict[int, ast.AST] = {}

        def mark(fn):
            traced.setdefault(id(fn), fn)

        for fns in index.values():
            for fn in fns:
                if any(_mentions_jit(d) for d in fn.decorator_list):
                    mark(fn)
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call):
                tail = dotted(n.func).split(".")[-1]
                if tail in TRACER_TAILS and n.args:
                    for name in _fn_names_from_arg(n.args[0]):
                        for fn in index.get(name, ()):
                            mark(fn)

        # transitive: traced code calling a same-module function by name
        work = list(traced.values())
        while work:
            fn = work.pop()
            for n in ast.walk(fn):
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                    for callee in index.get(n.func.id, ()):
                        if id(callee) not in traced:
                            traced[id(callee)] = callee
                            work.append(callee)

        for fn in traced.values():
            yield from self._check_traced(mod, fn)

    # -- impurity scan ------------------------------------------------------

    def _check_traced(
        self, mod: SourceModule, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> Iterable[Finding]:
        where = f"JAX-traced function {fn.name}()"
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                name = dotted(n.func)
                parts = name.split(".")
                if parts[0] == "time" and len(parts) == 2:
                    yield mod.finding(
                        self.name, n,
                        f"{name}() inside {where}: the clock is read once "
                        f"at trace time, not per step",
                    )
                elif parts[-1] in METRIC_METHODS and parts[0] in (
                    "self", "obs",
                ) or name.startswith(("logger.", "logging.")):
                    yield mod.finding(
                        self.name, n,
                        f"{name}() inside {where}: host side effect fires "
                        f"at trace time, not per step",
                    )
                elif parts[-1] in OBS_GETTERS:
                    yield mod.finding(
                        self.name, n,
                        f"{name}() inside {where}: observability handles "
                        f"must stay outside traced code",
                    )
                elif name == "print":
                    yield mod.finding(
                        self.name, n,
                        f"print() inside {where}: prints once at trace "
                        f"time — use jax.debug.print for in-trace output",
                    )
                elif parts[0] == "threading" or parts[0] == "_threading":
                    yield mod.finding(
                        self.name, n,
                        f"{name}() inside {where}: threading primitives "
                        f"must not be created or used under tracing",
                    )
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    ce_name = dotted(item.context_expr)
                    if "lock" in ce_name.lower():
                        yield mod.finding(
                            self.name, item.context_expr,
                            f"lock acquisition ({ce_name}) inside {where}: "
                            f"acquired once at trace time and can deadlock "
                            f"the prefetch compile thread",
                        )
            elif isinstance(n, ast.Attribute):
                if (
                    isinstance(n.ctx, (ast.Store, ast.Del))
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    yield mod.finding(
                        self.name, n,
                        f"self.{n.attr} mutated inside {where}: host-side "
                        f"state written at trace time, not per step",
                    )
                elif (
                    n.attr == "environ"
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "os"
                ):
                    yield mod.finding(
                        self.name, n,
                        f"os.environ read inside {where}: environment is "
                        f"captured once at trace time",
                    )
