"""xlalint: static analysis over the COMPILED programs the engine runs.

dlint (:mod:`.core` + the ``rules_*`` modules) checks the Python source;
this module checks the artifact that actually executes on the
accelerator — the post-GSPMD, post-optimization HLO of every AOT
executable in the engine's compile cache (decode blocks, lane-prefill
chunk programs, spec-verify buckets, ``kv_adopt``/``kv_publish`` copy
programs), via ``Compiled.as_text()`` and ``cost_analysis()``. The
invariants it enforces are exactly the ones the type system never sees:

* **collective census** — only the collectives a program family is
  allowed to lower (psums, the logits gather, ring permutes), and no
  all-gather whose result reassembles a full weight/embed table on
  every chip (the classic silent-regather perf cliff; the embed table
  check used to live as a one-off regex test in
  ``tests/test_parallel.py``);
* **donation honored** — every donated buffer (the KV cache /
  page-pool trees under ``donate_argnums``) must appear in the
  executable's ``input_output_alias`` map; a dropped donation is
  silent double-HBM;
* **no host round-trips** — no host-callback ``custom-call``s,
  infeed/outfeed, send/recv, or f64 in hot-path programs;
* **dtype policy** — weight-path dots must not silently upcast to an
  f32 accumulate-AND-STORE when the engine computes in bf16;
* **cost budget** — per-program ``bytes_accessed``/``flops`` ceilings
  derived from :func:`dllama_tpu.obs.cost.program_cost_ceilings`
  roofline math.

Three surfaces run it: ``python -m dllama_tpu.analysis --hlo`` (builds
a tiny CPU engine, pre-compiles the admission program set, lints it
against ``xlalint-baseline.json`` — the CI gate), the engine itself
(every AOT compile is linted as it is built: warn-by-default,
``DLLAMA_XLALINT=strict`` raises :class:`XlalintError`,
``DLLAMA_XLALINT=0`` disables), and ``GET /v1/debug/xlalint`` on the
API server. Baseline semantics are shared with dlint
(``rule::path::message`` fingerprints, no line numbers); see
docs/static_analysis.md.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import sys
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Iterable

from .core import (
    Finding,
    apply_baseline,
    load_baseline,
)

if TYPE_CHECKING:  # engine types only for annotations; jax stays lazy
    from ..runtime.engine import InferenceEngine

XLALINT_BASELINE_NAME = "xlalint-baseline.json"

#: Collectives a sharded forward step may legitimately lower: psum
#: (all-reduce), the vocab-sharded logits gather (all-gather),
#: reduce-scatter from GSPMD rewrites, the sp ring / pp stage permutes,
#: and all-to-all (XLA's distributed sort — the on-device top-p
#: sampling path — lowers through it). ``collective-broadcast`` is NOT
#: in the set: nothing in the forward should need it today.
FORWARD_COLLECTIVES = frozenset(
    {
        "all-reduce",
        "all-gather",
        "reduce-scatter",
        "collective-permute",
        "all-to-all",
    }
)


class XlalintError(RuntimeError):
    """Raised (under ``DLLAMA_XLALINT=strict``) when a freshly compiled
    program carries a new xlalint finding."""


@dataclass(frozen=True)
class FamilyPolicy:
    """Declarative per-program-family policy the HLO rules check."""

    #: collective op names allowed to appear (base names; async
    #: ``-start``/``-done`` forms are normalized before the check)
    allowed_collectives: frozenset = FORWARD_COLLECTIVES
    #: largest legal all-gather RESULT, in elements (0 = unlimited).
    max_allgather_elements: int = 0
    #: trailing-two result dims that mean "a full weight/embed table
    #: got reassembled" — e.g. {(vocab, dim), (dim, vocab)}
    forbidden_gather_dims: frozenset = frozenset()
    #: reject host-callback custom-calls / infeed / outfeed / send/recv
    forbid_host: bool = True
    #: reject any f64 tensor anywhere in the program
    forbid_f64: bool = True
    #: widest dtype a dot may STORE its result in, bits (0 = unlimited)
    max_dot_store_bits: int = 32
    #: flag bf16/f16 -> f32 convert feeding a dot that stores f32
    #: (the silent accumulate-and-store upcast); off unless the engine
    #: computes in a sub-f32 dtype
    forbid_f32_upcast_store: bool = False


@dataclass(frozen=True)
class HloProgram:
    """One compiled executable, as the rules see it."""

    name: str  # compile-cache key, stringified
    family: str  # engine step kind: decode_lanes, kv_adopt, ...
    hlo_text: str
    cost: dict | None  # {flops, bytes_accessed} or None
    expected_aliases: int  # donated leaves that must alias (0 = none)
    policy: FamilyPolicy
    bytes_budget: float = 0.0  # 0 = no ceiling
    flops_budget: float = 0.0

    @property
    def path(self) -> str:
        """Pseudo-path findings anchor to (stable across runs)."""
        return f"hlo://{self.family}/{self.name}"


@dataclass(frozen=True)
class HloFinding(Finding):
    """A Finding with a free-form ``detail`` that is RENDERED but not
    fingerprinted — raw cost numbers go here so the baseline stays
    stable across backends while the report stays concrete."""

    detail: str = ""

    def render(self) -> str:
        base = super().render()
        return f"{base} [{self.detail}]" if self.detail else base


class HloRule:
    """Base class for compiled-program rules (see rules_hlo)."""

    name = ""
    description = ""

    def check(self, prog: HloProgram) -> Iterable[Finding]:
        return ()


def all_hlo_rules() -> list:
    """Every registered HLO rule, instantiated (lazy import so this
    module stays importable without pulling the rule module first)."""
    from .rules_hlo import (
        CollectiveCensusRule,
        CostBudgetRule,
        DonationRule,
        DtypePolicyRule,
        HostRoundTripRule,
    )

    return [
        CollectiveCensusRule(),
        DonationRule(),
        HostRoundTripRule(),
        DtypePolicyRule(),
        CostBudgetRule(),
    ]


def lint_programs(
    programs: Iterable[HloProgram], rules: Iterable[HloRule] | None = None
) -> list[Finding]:
    findings: list[Finding] = []
    rule_list = list(rules) if rules is not None else all_hlo_rules()
    for prog in programs:
        for rule in rule_list:
            findings.extend(rule.check(prog))
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings


# -- engine integration -----------------------------------------------------

def _tree_bytes(specs: Any) -> int:
    import jax
    import numpy as np

    return sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(specs)
    )


def _tree_elems(specs: Any) -> int:
    import jax
    import numpy as np

    return sum(
        int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(specs)
    )


def _tree_nleaves(specs: Any) -> int:
    import jax

    return len(jax.tree.leaves(specs))


def _key_steps_tokens(key: Any, batch: int) -> tuple[int, int]:
    """(loop steps, tokens per forward) of a compile-cache key — the
    scale inputs to the cost budget. Plain ``(t, greedy, window)`` keys
    are prefill chunks; tagged keys carry their width at index 1."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        kind = key[0]
        n = int(key[1]) if len(key) > 1 else 1
        if kind in ("block", "lane_block", "lane_block_paged", "draft_step"):
            # draft_step autoregresses k greedy draft-model forwards
            return n, batch
        # lane_prefill / lane_verify / score / kv_*: one forward, n wide
        return 1, n * batch
    if isinstance(key, tuple) and key:
        return 1, int(key[0]) * batch
    return 1, batch


def engine_policies(engine: "InferenceEngine") -> dict:
    """Per-family policies for THIS engine: forbidden regather shapes
    from the model header (only meaningful when weights are actually
    sharded, tp > 1), the logits gather as the biggest legal all-gather,
    and the bf16 upcast check only when the engine computes in bf16."""
    import jax.numpy as jnp

    h = engine.header
    sharded = engine.tp > 1
    tables = frozenset(
        d
        for a, b in (
            (h.vocab_size, h.dim),
            (h.q_dim, h.dim),
            (h.kv_dim, h.dim),
            (h.ff_dim, h.dim),
        )
        for d in ((a, b), (b, a))
    ) if sharded else frozenset()
    max_ag = (
        4 * engine.batch_size * max(engine.prefill_buckets) * h.vocab_size
        if sharded
        else 0
    )
    bf16 = engine.dtype == jnp.bfloat16
    fwd = FamilyPolicy(
        forbidden_gather_dims=tables,
        max_allgather_elements=max_ag,
        forbid_f32_upcast_store=bf16,
    )
    copy = FamilyPolicy(
        allowed_collectives=frozenset(),  # pure shard-local copies
        forbid_f32_upcast_store=False,
    )
    return {
        "prefill": fwd,
        "decode_block": fwd,
        "decode_lanes": fwd,
        "prefill_lane": fwd,
        "verify_lanes": fwd,
        "score": fwd,
        "kv_adopt": copy,
        "kv_publish": copy,
        "kv_page_copy": copy,
        # resident draft model (PR 18): plain forwards over the draft
        # checkpoint — same regather/upcast rules as the target's
        "draft_prefill": fwd,
        "draft_step": fwd,
    }


def _engine_program(
    engine: "InferenceEngine", key: Any, fn: Any, policies: dict
) -> HloProgram | None:
    """Build the HloProgram for one compile-cache entry, or None when
    the entry exposes no executable (lazily jitted programs)."""
    from ..obs.cost import extract_cost, program_cost_ceilings

    as_text = getattr(fn, "as_text", None)
    if not callable(as_text):
        return None
    try:
        txt = as_text()
    except Exception:
        return None
    if not isinstance(txt, str) or not txt:
        return None
    family = engine._key_kind(key)
    policy = policies.get(family, FamilyPolicy())
    # draft-model programs run over the DRAFT checkpoint's params and
    # its own KV cache — budget them against those trees, not the
    # target's (a tiny draft linted against the big target's ceilings
    # would never trip the gate)
    draft = family in ("draft_prefill", "draft_step")
    param_specs = (
        engine._draft_param_specs if draft else engine._param_specs
    )
    cache_specs = (
        engine._draft_cache_specs if draft else engine._cache_specs
    )
    cache_b = _tree_bytes(cache_specs)
    pool_b = (
        _tree_bytes(engine._kv_pool_specs)
        if engine._kv_pool_specs is not None and not draft
        else 0
    )
    steps, tokens = _key_steps_tokens(key, engine.batch_size)
    # pool-native lane programs (PR 16) share their family with the
    # slab variants but donate the POOL, not the lane cache, and pay
    # page-indirection traffic the ceiling must cover
    paged = (
        isinstance(key, tuple)
        and bool(key)
        and isinstance(key[0], str)
        and key[0].endswith("_paged")
    )
    ceilings = program_cost_ceilings(
        family,
        steps=steps,
        tokens=tokens,
        param_bytes=_tree_bytes(param_specs),
        cache_bytes=cache_b,
        pool_bytes=pool_b,
        param_elems=_tree_elems(param_specs),
        cache_elems=_tree_elems(cache_specs),
        paged=paged,
    )
    if paged or family in ("kv_publish", "kv_page_copy"):
        expected = (
            _tree_nleaves(engine._kv_pool_specs)
            if engine._kv_pool_specs is not None
            else 0
        )
    else:
        expected = _tree_nleaves(cache_specs)
    return HloProgram(
        name=str(key),
        family=family,
        hlo_text=txt,
        cost=extract_cost(fn),
        expected_aliases=expected,
        policy=policy,
        bytes_budget=ceilings["bytes_accessed"],
        flops_budget=ceilings["flops"],
    )


def engine_programs(
    engine: "InferenceEngine",
) -> tuple[list[HloProgram], list[str]]:
    """(lintable programs, skipped keys) from the engine's compile
    cache. Lazily jitted entries (plain prefill/score steps under
    ``DLLAMA_WINDOW_PRECOMPILE=0``, or never-called jits) expose no
    executable and are reported as skipped, never silently dropped."""
    with engine._compile_lock:
        items = list(engine._compiled.items())
    policies = engine_policies(engine)
    programs: list[HloProgram] = []
    skipped: list[str] = []
    for key, fn in items:
        prog = _engine_program(engine, key, fn, policies)
        if prog is None:
            skipped.append(str(key))
        else:
            programs.append(prog)
    return programs, skipped


def repo_root() -> pathlib.Path:
    # analysis/ -> dllama_tpu/ -> repo root (same rule as __main__)
    return pathlib.Path(__file__).resolve().parent.parent.parent


def default_baseline_path() -> pathlib.Path:
    return repo_root() / XLALINT_BASELINE_NAME


def lint_engine_report(
    engine: "InferenceEngine", baseline: set | None = None
) -> dict:
    """The ``engine.xlalint_report()`` / ``GET /v1/debug/xlalint``
    payload: every finding split new-vs-baselined, plus the per-program
    census so an operator can see what was checked (and what was
    skipped for having no executable)."""
    if baseline is None:
        baseline = load_baseline(default_baseline_path())
    programs, skipped = engine_programs(engine)
    findings = lint_programs(programs)
    new, baselined, stale = apply_baseline(findings, baseline)
    return {
        "n_programs": len(programs),
        "skipped": skipped,
        "new_findings": [f.render() for f in new],
        "baselined_findings": [f.render() for f in baselined],
        "stale_baseline_entries": sorted(stale),
        "programs": [
            {
                "name": p.name,
                "family": p.family,
                "cost": p.cost,
                "expected_aliases": p.expected_aliases,
                "bytes_budget": p.bytes_budget,
                "flops_budget": p.flops_budget,
            }
            for p in programs
        ],
    }


def lint_engine_key(
    engine: "InferenceEngine", key: Any, baseline: set | None = None
) -> list[Finding]:
    """New (non-baselined) findings for ONE just-compiled program — the
    per-compile hook the engine calls after every AOT build."""
    if baseline is None:
        baseline = load_baseline(default_baseline_path())
    with engine._compile_lock:
        fn = engine._compiled.get(key)
    if fn is None:
        return []
    prog = _engine_program(engine, key, fn, engine_policies(engine))
    if prog is None:
        return []
    new, _, _ = apply_baseline(lint_programs([prog]), baseline)
    return new


# -- CLI (--hlo mode) -------------------------------------------------------

_TINY_CFG = dict(
    dim=64,
    hidden_dim=160,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    vocab_size=256,
    seq_len=64,
)


def _write_tiny_model(path: str, seed: int = 0) -> None:
    """A tiny random F32 `.m` model for the CLI's self-contained engine
    (mirrors tests/helpers.make_tiny_model, which must stay test-only)."""
    import numpy as np

    from ..formats import FloatType
    from ..formats.model_file import LlmArch
    from ..formats.writer import write_header, write_tensor

    cfg = _TINY_CFG
    rng = np.random.default_rng(seed)
    d, hd = cfg["dim"], cfg["head_dim"]
    q_dim = hd * cfg["n_heads"]
    kv_dim = hd * cfg["n_kv_heads"]
    ff = cfg["hidden_dim"]

    def t(*shape: int) -> Any:
        return (rng.standard_normal(shape) * 0.08).astype(np.float32)

    header = {
        "version": 0,
        "arch_type": int(LlmArch.LLAMA),
        "dim": d,
        "hidden_dim": ff,
        "n_layers": cfg["n_layers"],
        "n_heads": cfg["n_heads"],
        "n_kv_heads": cfg["n_kv_heads"],
        "n_experts": 0,
        "n_active_experts": 0,
        "vocab_size": cfg["vocab_size"],
        "max_seq_len": cfg["seq_len"],
        "hidden_act": 1,
        "rope_theta": 10000,
        "weights_float_type": int(FloatType.F32),
        "head_dim": hd,
        "norm_epsilon": 5,
    }
    with open(path, "wb") as f:
        write_header(f, header)
        write_tensor(f, t(cfg["vocab_size"], d), FloatType.F32)
        for _ in range(cfg["n_layers"]):
            write_tensor(f, t(q_dim, d), FloatType.F32)
            write_tensor(f, t(kv_dim, d), FloatType.F32)
            write_tensor(f, t(kv_dim, d), FloatType.F32)
            write_tensor(f, t(d, q_dim), FloatType.F32)
            write_tensor(f, t(ff, d), FloatType.F32)
            write_tensor(f, t(d, ff), FloatType.F32)
            write_tensor(f, t(ff, d), FloatType.F32)
            write_tensor(f, 1.0 + t(d), FloatType.F32)
            write_tensor(f, 1.0 + t(d), FloatType.F32)
        write_tensor(f, 1.0 + t(d), FloatType.F32)
        write_tensor(f, t(cfg["vocab_size"], d), FloatType.F32)


def _ensure_virtual_devices(n: int = 2) -> None:
    """Ask for n virtual CPU devices so the CLI engine can run tp > 1
    (collective census with real all-gathers). Only effective when jax
    is not imported yet and the flag is not already set."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def build_cli_engine() -> "InferenceEngine":
    """The tiny self-contained engine the ``--hlo`` CLI lints: tp=2 when
    two devices are available (so the census sees the real psums and
    the logits gather), lanes + a KV pool + speculation buckets so every
    AOT program family is present, and every admission program compiled
    synchronously before returning."""
    # no double-reporting: the CLI prints findings itself, so the
    # per-compile warn hook stays off while this engine builds
    os.environ.setdefault("DLLAMA_XLALINT", "0")
    _ensure_virtual_devices(2)
    import tempfile

    import jax
    import jax.numpy as jnp

    from ..runtime.engine import InferenceEngine

    tp = 2 if len(jax.devices()) >= 2 else 1
    d = tempfile.mkdtemp(prefix="xlalint-")
    mp = os.path.join(d, "tiny.m")
    _write_tiny_model(mp)
    engine = InferenceEngine(
        mp,
        tp=tp,
        dtype=jnp.float32,
        temperature=0.0,
        batch_size=4,
        prefill_buckets=(1, 8, 32),
    )
    engine.init_kv_pool(page_size=8)
    engine.rehearse_admission(block_size=8, spec_k=2, wait=True)
    # pool-native paged families (PR 16): flip native on and rehearse
    # again — the compile cache keeps the slab programs, so BOTH KV
    # paths' executables go under the lint in one run
    engine.init_kv_pool(page_size=8, native=True)
    engine.rehearse_admission(block_size=8, spec_k=2, wait=True)
    # resident-draft families (PR 18): the tiny model doubles as its own
    # draft checkpoint (same tokenizer by construction), so the
    # draft_prefill/draft_step buckets compile and go under the lint too
    engine.init_draft_model(mp)
    engine.rehearse_admission(block_size=8, spec_k=2, wait=True)
    return engine


def run_hlo_cli(args: Any) -> int:
    """``python -m dllama_tpu.analysis --hlo``: build the tiny engine,
    lint every compiled program, apply/maintain the xlalint baseline.
    Exit codes match dlint: 0 clean, 1 new findings."""
    engine = build_cli_engine()
    programs, skipped = engine_programs(engine)
    findings = lint_programs(programs)

    baseline_path = (
        pathlib.Path(args.baseline)
        if args.baseline
        else default_baseline_path()
    )
    if args.update_baseline:
        write_baseline_fingerprints(
            baseline_path, (f.fingerprint() for f in findings)
        )
        print(
            f"xlalint baseline written: {len(findings)} finding(s) -> "
            f"{baseline_path}"
        )
        return 0

    baseline = set() if args.no_baseline else load_baseline(baseline_path)
    new, baselined, stale = apply_baseline(findings, baseline)

    if args.prune:
        kept = baseline - stale
        write_baseline_fingerprints(baseline_path, kept)
        print(
            f"xlalint baseline pruned: {len(stale)} stale entr"
            f"{'y' if len(stale) == 1 else 'ies'} removed, "
            f"{len(kept)} kept -> {baseline_path}"
        )
        return 0

    for f in new:
        print(f.render())
    if not args.quiet:
        if stale:
            print(
                f"note: {len(stale)} stale xlalint baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} no longer match any "
                f"finding — prune with --hlo --prune"
            )
        n_rules = len(all_hlo_rules())
        print(
            f"xlalint: {len(programs)} compiled programs ({len(skipped)} "
            f"skipped: no executable), {n_rules} rules, {len(new)} new "
            f"finding(s), {len(baselined)} baselined"
        )
    return 1 if new else 0


def write_baseline_fingerprints(
    path: pathlib.Path, fingerprints: Iterable[str]
) -> None:
    """Rewrite a baseline file from raw fingerprints (the --prune path,
    where stale entries have no live Finding to round-trip through)."""
    import json

    data = {
        "comment": (
            "xlalint baseline: fingerprints of pre-existing compiled-"
            "program findings allowed to persist. Regenerate with "
            "`python -m dllama_tpu.analysis --hlo --update-baseline`; "
            "prune stale entries with `--hlo --prune`."
        ),
        "findings": sorted(set(fingerprints)),
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def make_program(
    hlo_text: str,
    *,
    name: str = "toy",
    family: str = "decode_lanes",
    policy: FamilyPolicy | None = None,
    cost: dict | None = None,
    expected_aliases: int = 0,
    bytes_budget: float = 0.0,
    flops_budget: float = 0.0,
) -> HloProgram:
    """Convenience constructor for tests and ad-hoc linting of a single
    HLO dump (seeded-violation fixtures build programs through this)."""
    return HloProgram(
        name=name,
        family=family,
        hlo_text=hlo_text,
        cost=cost,
        expected_aliases=expected_aliases,
        policy=policy if policy is not None else FamilyPolicy(),
        bytes_budget=bytes_budget,
        flops_budget=flops_budget,
    )


def replace_policy(prog: HloProgram, **changes: Any) -> HloProgram:
    """A program with its policy fields replaced (tests tighten one
    knob at a time)."""
    return dataclasses.replace(
        prog, policy=dataclasses.replace(prog.policy, **changes)
    )
