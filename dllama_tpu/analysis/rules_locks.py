"""guarded-attrs: lock discipline on shared attributes.

The project convention (kv/manager.py, obs/*, the engine compile cache):
a class that creates a ``threading.Lock``/``RLock``/``Condition`` in an
attribute guards some of its state with it. This rule infers the guarded
set — any attribute *written* while holding one of the class's locks in
a non-``__init__`` method — and then flags every read or write of a
guarded attribute performed without holding a class lock.

What counts as a write (all of these mutate shared state):

* plain / augmented / annotated assignment to ``self.X``;
* subscript stores and deletes (``self.X[k] = v``, ``del self.X[k]``);
* calls to container mutators (``self.X.append(...)``, ``.pop``,
  ``.clear``, ``.update`` …).

Exemptions, matching how the code is actually safe:

* ``__init__`` — construction happens-before publication to any other
  thread, so unlocked writes there are fine (and do not mark an
  attribute as guarded by themselves);
* methods named ``*_locked`` — the project suffix for "caller holds the
  lock" helpers;
* bodies of functions nested inside a method are analyzed as holding NO
  lock even when defined inside a ``with`` block: closures outlive the
  block (thread targets, callbacks), so assuming the lock there would
  hide exactly the bug this rule exists for.

Intentional unlocked accesses (racy-but-benign monitoring reads, double-
checked locking fast paths) take an inline
``# dlint: disable=guarded-attrs — why`` with the why spelled out.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import Finding, Rule, SourceModule, is_self_attr, iter_methods

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
# lockwatch.make_lock()/make_condition() return drop-in locks
LOCK_FACTORY_FUNCS = {"make_lock", "make_condition"}

MUTATORS = {
    "append", "appendleft", "pop", "popleft", "popitem", "clear", "extend",
    "extendleft", "insert", "remove", "update", "add", "discard",
    "setdefault", "sort", "reverse",
}


def _is_lock_factory(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in LOCK_FACTORIES | LOCK_FACTORY_FUNCS
    if isinstance(fn, ast.Name):
        return fn.id in LOCK_FACTORIES | LOCK_FACTORY_FUNCS
    return False


class _Access:
    __slots__ = ("attr", "method", "line", "locks", "is_write")

    def __init__(self, attr, method, line, locks, is_write):
        self.attr = attr
        self.method = method
        self.line = line
        self.locks = locks
        self.is_write = is_write


class GuardedAttrsRule(Rule):
    name = "guarded-attrs"
    description = (
        "attributes written under a class lock must not be read or "
        "written elsewhere without it"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)

    # -- per-class analysis -------------------------------------------------

    def _check_class(
        self, mod: SourceModule, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return
        accesses: list[_Access] = []
        for meth in iter_methods(cls):
            self._visit_stmts(
                meth.body, frozenset(), meth.name, lock_attrs, accesses
            )
        # guarded = written under a lock outside __init__
        guarded: dict[str, tuple[str, str]] = {}
        for a in sorted(accesses, key=lambda a: (a.attr, a.method, a.line)):
            if a.is_write and a.locks and a.method != "__init__":
                guarded.setdefault(a.attr, (sorted(a.locks)[0], a.method))
        for a in accesses:
            if a.attr not in guarded:
                continue
            if a.locks or a.method == "__init__":
                continue
            if a.method.endswith("_locked"):
                continue  # project convention: caller holds the lock
            lock, writer = guarded[a.attr]
            kind = "written" if a.is_write else "read"
            yield mod.finding(
                self.name,
                a.line,
                f"{cls.name}.{a.attr} is guarded by self.{lock} "
                f"(written under it in {writer}()) but {kind} without a "
                f"lock in {a.method}()",
            )

    def _lock_attrs(self, cls: ast.ClassDef) -> set[str]:
        found: set[str] = set()
        for meth in iter_methods(cls):
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign) and _is_lock_factory(
                    node.value
                ):
                    for t in node.targets:
                        if is_self_attr(t):
                            found.add(t.attr)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if _is_lock_factory(node.value) and is_self_attr(
                        node.target
                    ):
                        found.add(node.target.attr)
        return found

    # -- traversal with a held-locks context --------------------------------

    def _visit_stmts(
        self,
        stmts: list,
        held: frozenset,
        method: ast.FunctionDef | ast.AsyncFunctionDef,
        lock_attrs: set,
        out: list,
    ) -> None:
        for s in stmts:
            if isinstance(s, (ast.With, ast.AsyncWith)):
                newly = set()
                for item in s.items:
                    ce = item.context_expr
                    self._scan(ce, held, method, lock_attrs, out)
                    if is_self_attr(ce) and ce.attr in lock_attrs:
                        newly.add(ce.attr)
                    # `with self._lock` spelled as acquire-style contexts
                    # (e.g. `with self._lock.locked_scope()`) is out of
                    # convention; only the bare attribute form is a guard.
                self._visit_stmts(
                    s.body, held | frozenset(newly), method, lock_attrs, out
                )
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure may run after the with-block exits: no lock
                for d in s.decorator_list:
                    self._scan(d, held, method, lock_attrs, out)
                self._visit_stmts(
                    s.body, frozenset(), method, lock_attrs, out
                )
            elif isinstance(s, ast.ClassDef):
                continue  # nested classes analyzed independently
            elif isinstance(s, ast.If):
                self._scan(s.test, held, method, lock_attrs, out)
                self._visit_stmts(s.body, held, method, lock_attrs, out)
                self._visit_stmts(s.orelse, held, method, lock_attrs, out)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                self._scan(s.target, held, method, lock_attrs, out)
                self._scan(s.iter, held, method, lock_attrs, out)
                self._visit_stmts(s.body, held, method, lock_attrs, out)
                self._visit_stmts(s.orelse, held, method, lock_attrs, out)
            elif isinstance(s, ast.While):
                self._scan(s.test, held, method, lock_attrs, out)
                self._visit_stmts(s.body, held, method, lock_attrs, out)
                self._visit_stmts(s.orelse, held, method, lock_attrs, out)
            elif isinstance(s, ast.Try):
                self._visit_stmts(s.body, held, method, lock_attrs, out)
                for h in s.handlers:
                    self._visit_stmts(h.body, held, method, lock_attrs, out)
                self._visit_stmts(s.orelse, held, method, lock_attrs, out)
                self._visit_stmts(s.finalbody, held, method, lock_attrs, out)
            else:
                self._scan(s, held, method, lock_attrs, out)

    def _scan(
        self,
        node: ast.AST,
        held: frozenset,
        method: ast.FunctionDef | ast.AsyncFunctionDef,
        lock_attrs: set,
        out: list,
    ) -> None:
        """Record self-attribute reads/writes in an expression (or simple
        statement) subtree."""
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and is_self_attr(n):
                if n.attr in lock_attrs:
                    continue
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    out.append(_Access(n.attr, method, n.lineno, held, True))
                else:
                    out.append(_Access(n.attr, method, n.lineno, held, False))
            elif isinstance(n, ast.Subscript) and isinstance(
                n.ctx, (ast.Store, ast.Del)
            ):
                if is_self_attr(n.value) and n.value.attr not in lock_attrs:
                    out.append(
                        _Access(n.value.attr, method, n.lineno, held, True)
                    )
            elif isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ):
                tgt = n.func.value
                if (
                    n.func.attr in MUTATORS
                    and is_self_attr(tgt)
                    and tgt.attr not in lock_attrs
                ):
                    out.append(
                        _Access(tgt.attr, method, n.lineno, held, True)
                    )
