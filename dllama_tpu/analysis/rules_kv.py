"""retain-release: PagePool ownership must balance on every exit path.

The PR 6 review bugs were both refcount-pairing holes: a ``match``
without ``retain`` across a tick gap, and a publish path whose matched
pages could be evicted mid-flow. This rule walks every function that
touches a page pool (``*.retain(...)``, ``*.alloc(...)``,
``*.fork(...)`` on a receiver whose name ends in ``pool``) with a small
path-sensitive interpreter and checks the ownership protocol:

* a ``retain(E)`` / ``alloc()``->var / ``fork()``->var opens a token;
* ``release(E)``, storing into a ``*_lane_pages``-style map
  (subscript-store mentioning the token), ``tree.insert(... token ...)``
  and ``reset(...)`` close it (transfer of ownership IS balance —
  the new owner's release path takes over);
* returning the token hands ownership to the caller (closed here);
* every ``return`` / ``break`` / ``continue`` / fall-off-the-end must
  see zero open tokens (``finally`` closers count on return paths);
* while a token is open and not protected by a ``finally``/``except``
  that closes it, no *risky* call may run — a risky call is anything
  that can raise out of the accounting's control (``self.engine.*``,
  free functions, other objects); pool/tree/recorder/metric calls and
  builtins are safe. This is exactly the shape of the PR 6 bug: device
  work dispatched while holding unprotected page refs.

Path handling is approximate by design: ``if``/``try`` branch states
are tracked as sets (capped), loop bodies are evaluated once, and the
handler entry state over-approximates to "everything the body may have
opened". False positives get an inline
``# dlint: disable=retain-release — why`` at the opening site.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import Finding, Rule, SourceModule, dotted

MAX_STATES = 128

_SAFE_BUILTINS = {
    "len", "list", "min", "max", "sorted", "range", "int", "str", "float",
    "tuple", "set", "dict", "isinstance", "round", "sum", "zip", "enumerate",
    "abs", "repr", "print",
}
_SAFE_RECEIVER_PARTS = ("pool", "tree", "recorder", "logger", "logging")


class _Token:
    __slots__ = ("kind", "key", "line")

    def __init__(self, kind: str, key: str, line: int) -> None:
        self.kind = kind  # "retain" | "pages"
        self.key = key    # dotted expr ("mr.pages") or var name ("pages")
        self.line = line

    def __hash__(self):
        return hash((self.kind, self.key, self.line))

    def __eq__(self, other):
        return (self.kind, self.key, self.line) == (
            other.kind, other.key, other.line
        )

    def describe(self) -> str:
        verb = "retained" if self.kind == "retain" else "allocated"
        return f"pool pages {verb} at line {self.line} ({self.key!r})"


def _is_pool_call(node: ast.Call, names: tuple[str, ...]) -> bool:
    fn = node.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr in names
        and dotted(fn.value).split(".")[-1].endswith("pool")
    )


def _names_in(node: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.Name, ast.Attribute)):
            out.add(dotted(n))
    return out


def _call_is_risky(call: ast.Call) -> str | None:
    """Dotted callee name if the call can raise outside the accounting's
    control, else None."""
    fn = call.func
    name = dotted(fn)
    if isinstance(fn, ast.Name):
        return None if fn.id in _SAFE_BUILTINS else name
    if isinstance(fn, ast.Attribute):
        parts = name.split(".")
        if parts[0] == "self":
            if len(parts) >= 3 and parts[1] == "engine":
                return name  # device dispatch: the canonical risky call
            if any(p.endswith(_SAFE_RECEIVER_PARTS) for p in parts[:-1]):
                return None
            return None  # other self.* helpers: accounting-local
        if any(p.endswith(_SAFE_RECEIVER_PARTS) for p in parts[:-1]):
            return None
        return name
    return name


class _Ctx:
    def __init__(self) -> None:
        self.finally_closers: set[str] = set()   # token keys
        self.raise_protected: set[str] = set()   # token keys
        self.loop_entry: frozenset | None = None
        self.findings: list[tuple[int, str]] = []
        self.risk_reported: set[_Token] = set()

    def copy(self) -> "_Ctx":
        c = _Ctx.__new__(_Ctx)
        c.finally_closers = set(self.finally_closers)
        c.raise_protected = set(self.raise_protected)
        c.loop_entry = self.loop_entry
        c.findings = self.findings          # shared accumulator
        c.risk_reported = self.risk_reported
        return c


class RetainReleaseRule(Rule):
    name = "retain-release"
    description = (
        "PagePool retain/alloc/fork must be released or ownership-"
        "transferred on every exit path, and protected across calls "
        "that may raise"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._touches_pool(node):
                    yield from self._check_function(mod, node)

    def _touches_pool(
        self, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and _is_pool_call(
                n, ("retain", "alloc", "fork")
            ):
                return True
        return False

    # -- interpreter --------------------------------------------------------

    def _check_function(
        self, mod: SourceModule, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> Iterator[Finding]:
        ctx = _Ctx()
        states = {frozenset()}
        states = self._eval(fn.body, states, ctx)
        end_line = getattr(fn, "end_lineno", fn.lineno)
        for st in states:
            for tok in st:
                ctx.findings.append((
                    end_line,
                    f"{tok.describe()} is neither released nor ownership-"
                    f"transferred on some path through {fn.name}()",
                ))
        seen: set[tuple[int, str]] = set()
        for line, msg in ctx.findings:
            if (line, msg) in seen:
                continue
            seen.add((line, msg))
            yield mod.finding(self.name, line, msg)

    def _eval(
        self, stmts: list, states: set, ctx: "_Ctx"
    ) -> set:
        for s in stmts:
            if len(states) > MAX_STATES:
                states = {frozenset().union(*states)}
            if isinstance(s, ast.If):
                a = self._eval(s.body, set(states), ctx.copy())
                b = self._eval(s.orelse, set(states), ctx.copy())
                states = a | b
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                states = self._eval(s.body, states, ctx)
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                inner = ctx.copy()
                inner.loop_entry = frozenset().union(*states) if states \
                    else frozenset()
                body_states = self._eval(s.body, set(states), inner)
                states = states | body_states
                states = self._eval(s.orelse, states, ctx)
            elif isinstance(s, ast.Try):
                states = self._eval_try(s, states, ctx)
            elif isinstance(s, ast.Return):
                self._exit_check(s, states, ctx, "return")
                return set()  # path ends
            elif isinstance(s, (ast.Break, ast.Continue)):
                self._loop_exit_check(s, states, ctx)
                return set()
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue  # nested defs analyzed on their own
            elif isinstance(s, ast.Raise):
                # an explicit raise with open unprotected tokens leaks
                self._exit_check(s, states, ctx, "raise", protected_ok=True)
                return set()
            else:
                states = {self._apply(s, st, ctx) for st in states}
        return states

    def _eval_try(
        self, s: ast.Try, states: set, ctx: "_Ctx"
    ) -> set:
        fin_closers = self._closers(s.finalbody)
        exc_closers = set()
        for h in s.handlers:
            exc_closers |= self._closers(h.body)
        body_ctx = ctx.copy()
        body_ctx.finally_closers |= fin_closers
        body_ctx.raise_protected |= fin_closers | exc_closers
        entry = set(states)
        after_body = self._eval(s.body, set(states), body_ctx)
        after_body = self._eval(s.orelse, after_body, body_ctx)
        # handlers start from "anything the body may have opened"
        handler_entry = entry | after_body
        out = set(after_body)
        for h in s.handlers:
            out |= self._eval(h.body, set(handler_entry), ctx.copy())
        out = self._eval(s.finalbody, out, ctx)
        return out

    # -- transfer / open / close extraction ---------------------------------

    def _closers(self, stmts: list) -> set[str]:
        """Token KEYS closed anywhere in a statement list (used to mark
        finally/except protection)."""
        keys: set[str] = set()
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, ast.Call):
                    fn = n.func
                    if _is_pool_call(n, ("release",)) and n.args:
                        keys.add(dotted(n.args[0]))
                    elif isinstance(fn, ast.Attribute) and fn.attr in (
                        "reset", "clear",
                    ):
                        keys.add("*")
                    elif isinstance(fn, ast.Attribute) and fn.attr == \
                            "insert":
                        for a in n.args:
                            keys |= _names_in(a)
        return keys

    def _apply(
        self, stmt: ast.stmt, state: frozenset, ctx: "_Ctx"
    ) -> frozenset:
        opened: list[_Token] = []
        closed_keys: set[str] = set()
        close_all = False

        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if _is_pool_call(n, ("retain",)) and n.args:
                opened.append(
                    _Token("retain", dotted(n.args[0]), n.lineno)
                )
            elif _is_pool_call(n, ("release",)) and n.args:
                closed_keys.add(dotted(n.args[0]))
            elif isinstance(fn, ast.Attribute) and fn.attr == "reset":
                close_all = True
            elif isinstance(fn, ast.Attribute) and fn.attr == "insert":
                # tree.insert(tokens, pages, ...) — ownership transfer
                for a in list(n.args) + [k.value for k in n.keywords]:
                    closed_keys |= _names_in(a)

        # alloc/fork results bound to a name open a "pages" token
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            has_alloc = value is not None and any(
                isinstance(n, ast.Call)
                and _is_pool_call(n, ("alloc", "fork"))
                for n in ast.walk(value)
            )
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            if has_alloc and not isinstance(stmt, ast.AugAssign):
                for t in targets:
                    if isinstance(t, (ast.Name, ast.Attribute)):
                        opened.append(
                            _Token("pages", dotted(t), stmt.lineno)
                        )
            # subscript-store transfer: self._lane_pages[lane] = pages
            for t in targets:
                if isinstance(t, ast.Subscript) and value is not None:
                    closed_keys |= _names_in(value)

        # risky-call audit BEFORE applying closers: the call runs while
        # the tokens opened earlier are still live (tokens opened in THIS
        # statement are its own result and cannot leak through it)
        risky = None
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                risky = _call_is_risky(n)
                if risky:
                    break
        if risky:
            for tok in state:
                if tok.key in ctx.raise_protected or \
                        "*" in ctx.raise_protected:
                    continue
                if tok in ctx.risk_reported:
                    continue
                ctx.risk_reported.add(tok)
                ctx.findings.append((
                    stmt.lineno,
                    f"{tok.describe()} may leak if {risky}() raises here "
                    f"(no enclosing finally/except releases it)",
                ))

        new = set(state)
        if close_all:
            new.clear()
        else:
            new = {
                t for t in new
                if t.key not in closed_keys
            }
        new.update(opened)
        # token self-close within the same statement
        # (e.g. release(alloc(...)) — degenerate but keeps things sane)
        if closed_keys:
            new = {t for t in new if t.key not in closed_keys}
        return frozenset(new)

    # -- exit checks --------------------------------------------------------

    def _exit_check(self, stmt, states, ctx, how, protected_ok=False):
        returned = (
            _names_in(stmt.value)
            if isinstance(stmt, ast.Return) and stmt.value is not None
            else set()
        )
        for st in states:
            for tok in st:
                if tok.key in ctx.finally_closers or \
                        "*" in ctx.finally_closers:
                    continue  # finally releases it on the way out
                if tok.key in returned:
                    continue  # ownership handed to the caller
                if protected_ok and (
                    tok.key in ctx.raise_protected
                    or "*" in ctx.raise_protected
                ):
                    continue
                ctx.findings.append((
                    stmt.lineno,
                    f"{tok.describe()} is not released before the {how} "
                    f"at line {stmt.lineno}",
                ))

    def _loop_exit_check(self, stmt, states, ctx):
        entry = ctx.loop_entry or frozenset()
        kw = "break" if isinstance(stmt, ast.Break) else "continue"
        for st in states:
            for tok in st:
                if tok in entry:
                    continue
                if tok.key in ctx.finally_closers or \
                        "*" in ctx.finally_closers:
                    continue
                ctx.findings.append((
                    stmt.lineno,
                    f"{tok.describe()} is not released before the {kw} "
                    f"at line {stmt.lineno}",
                ))
