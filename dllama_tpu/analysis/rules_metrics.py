"""metrics-docs: registered ``dllama_*`` metrics and the operator doc
must agree in both directions.

This is the former ``scripts/check_metrics_docs.py`` lint folded into
the dlint framework so ``python -m dllama_tpu.analysis`` is the one
entrypoint that runs everything; the script survives as a thin shim
over this rule. Semantics are unchanged:

* source side — static scan of ``counter("dllama_...")`` /
  ``gauge(`` / ``histogram(`` registration calls across ``dllama_tpu/``
  and ``bench.py`` (registrations span lines, so the regex runs over
  whole file contents). Dynamically named metrics (the telemetry
  Counter's f-string template) have no literal name at the registration
  site and stay out of scope;
* doc side — every backticked ``dllama_*`` identifier in
  ``docs/serving_metrics.md``. The ``<name>`` placeholder in the
  template breaks the identifier pattern, so it never counts.

A metric registered but undocumented is silent telemetry nobody can
discover; documented but unregistered is a dashboard querying a
phantom.
"""

from __future__ import annotations

import re
from typing import Iterable

from .core import Finding, Repo, Rule

DOC_REL = "docs/serving_metrics.md"

_REGISTRATION = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*[\"'](dllama_[a-z0-9_]+)[\"']"
)
_DOC_NAME = re.compile(r"`(dllama_[a-z0-9_]+)`")


def registered_names(repo: Repo) -> dict[str, tuple[str, int]]:
    """metric name -> (path, line) of its first registration site."""
    names: dict[str, tuple[str, int]] = {}
    for mod in repo.modules:
        if not (
            mod.rel.startswith("dllama_tpu/") or mod.rel == "bench.py"
        ):
            continue
        for m in _REGISTRATION.finditer(mod.text):
            line = mod.text.count("\n", 0, m.start()) + 1
            names.setdefault(m.group(1), (mod.rel, line))
    return names


def documented_names(repo: Repo) -> dict[str, int]:
    doc = repo.root / DOC_REL
    if not doc.exists():
        return {}
    text = doc.read_text()
    names: dict[str, int] = {}
    for m in _DOC_NAME.finditer(text):
        names.setdefault(m.group(1), text.count("\n", 0, m.start()) + 1)
    return names


class MetricsDocsRule(Rule):
    name = "metrics-docs"
    description = (
        "every registered dllama_* metric is documented in "
        "docs/serving_metrics.md, and vice versa"
    )

    def check_repo(self, repo: Repo) -> Iterable[Finding]:
        code = registered_names(repo)
        doc = documented_names(repo)
        for name in sorted(set(code) - set(doc)):
            path, line = code[name]
            yield Finding(
                rule=self.name, path=path, line=line,
                message=(
                    f"metric {name} is registered here but missing from "
                    f"{DOC_REL}"
                ),
            )
        for name in sorted(set(doc) - set(code)):
            yield Finding(
                rule=self.name, path=DOC_REL, line=doc[name],
                message=(
                    f"metric {name} is documented but registered nowhere "
                    f"(dashboards would query a phantom)"
                ),
            )
