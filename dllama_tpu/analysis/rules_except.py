"""silent-except: broad exception handlers must leave evidence.

The serving path deliberately survives engine failures — the scheduler
loop, the KV manager and the chunked-admission machinery all contain
``except Exception`` blocks that degrade instead of crashing. That
policy is only safe while every such block EMITS something an operator
can find later: a flight-recorder event, a metric increment, or a
re-raise that hands the failure to a layer that does. A broad handler
that swallows the exception with none of those is how a chaos run "goes
green" while silently corrupting streams.

The rule walks ``except`` handlers in ``dllama_tpu/runtime/`` and
``dllama_tpu/kv/`` whose caught type is ``Exception`` / ``BaseException``
/ bare (or a tuple containing one of those) and flags any whose body
neither raises nor calls an evidence sink — ``.record(...)`` /
``.postmortem(...)`` (the recorder), ``.inc(...)`` / ``.observe(...)`` /
``.labels(...)`` (metric handles). Plain logging does NOT count: log
lines are not scrapeable and the repo's failure-path tests assert on
recorder events and metrics, not grep.

Narrow handlers (``except ValueError``) stay exempt — catching a
specific type is itself a statement of intent. Suppress a deliberate
silent site with ``# dlint: disable=silent-except — why``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Finding, Rule, SourceModule

SCOPED_PREFIXES = (
    "dllama_tpu/runtime/",
    "dllama_tpu/kv/",
    # the fleet front door's error paths (failover, spill, drain
    # forwarding) must leave evidence from day one — a router that
    # swallows a replica death silently defeats its own purpose
    "dllama_tpu/fleet/",
)
BROAD_TYPES = {"Exception", "BaseException"}
EVIDENCE_CALLS = {"record", "postmortem", "inc", "observe", "labels"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        if isinstance(node, ast.Name) and node.id in BROAD_TYPES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in BROAD_TYPES:
            return True
    return False


def _leaves_evidence(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in EVIDENCE_CALLS
        ):
            return True
    return False


class SilentExceptRule(Rule):
    name = "silent-except"
    description = (
        "broad except blocks in runtime/ and kv/ must re-raise or emit "
        "a recorder event / metric"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        if not mod.rel.startswith(SCOPED_PREFIXES):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _leaves_evidence(node):
                continue
            caught = (
                "bare except"
                if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            yield mod.finding(
                self.name,
                node,
                f"{caught} swallows the failure with no recorder event, "
                f"metric, or re-raise — the degraded-not-dead policy "
                f"requires evidence; record it or suppress with a reason",
            )
