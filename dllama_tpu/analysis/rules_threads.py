"""thread-hygiene: every thread is named, daemonized, and stoppable.

An anonymous thread is invisible in ``py-spy``/``faulthandler`` dumps
(the first tool reached for when the engine wedges — see the watchdog's
postmortems); a non-daemon thread turns Ctrl-C into a hang; a thread
with no join/stop path leaks across engine restarts in tests and keeps
mutating shared state after its owner is gone.

For every ``threading.Thread(...)`` construction:

* ``name=`` must be passed (convention: ``dllama-<role>``);
* ``daemon=True`` must be passed at construction (not assigned later —
  the window between ``start()`` and the assignment is exactly when an
  exception would leave it non-daemon);
* there must be a join/stop path: either the thread object lands in an
  attribute/variable that is ``.join()``-ed somewhere in the same
  class/function, or the owning class defines a ``stop``/``close``/
  ``shutdown``/``join`` method (the project's stop-event pattern —
  watchdog/scheduler loops exit when their stop flag is set). Bare
  ``threading.Thread(...).start()`` fire-and-forget constructions are
  flagged; where the lifetime is genuinely bounded and observed through
  another mechanism, say so in an inline
  ``# dlint: disable=thread-hygiene — why``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Finding, Rule, SourceModule, is_self_attr

STOP_METHODS = {"stop", "close", "shutdown", "join", "__exit__"}


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _class_has_stop_path(cls: ast.ClassDef, attr: str | None) -> bool:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in STOP_METHODS:
                return True
    if attr is not None:
        for node in ast.walk(cls):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and is_self_attr(node.func.value, attr)
            ):
                return True
    return False


def _joins_name(node: ast.AST, var: str) -> bool:
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == var
        ):
            return True
    return False


def _function_joins(
    fn: ast.FunctionDef | ast.AsyncFunctionDef, var: str
) -> bool:
    if _joins_name(fn, var):
        return True
    # the list idiom: threads = [Thread(...) for ...]; later
    # ``for t in threads: t.join()`` (possibly ``threads + [other]``)
    for node in ast.walk(fn):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        iter_names = {
            n.id for n in ast.walk(node.iter) if isinstance(n, ast.Name)
        }
        if var not in iter_names:
            continue
        if isinstance(node.target, ast.Name) and _joins_name(
            node, node.target.id
        ):
            return True
    return False


class ThreadHygieneRule(Rule):
    name = "thread-hygiene"
    description = (
        "threading.Thread must be named, daemonized at construction, "
        "and have a join/stop path"
    )

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        findings: list[Finding] = []
        self._walk(mod, mod.tree.body, cls=None, fn=None, out=findings)
        return findings

    def _walk(
        self,
        mod: SourceModule,
        stmts: list,
        cls: ast.ClassDef | None,
        fn: ast.FunctionDef | ast.AsyncFunctionDef | None,
        out: list,
    ) -> None:
        for s in stmts:
            if isinstance(s, ast.ClassDef):
                self._walk(mod, s.body, cls=s, fn=fn, out=out)
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(mod, s.body, cls=cls, fn=s, out=out)
            elif isinstance(s, (ast.If, ast.For, ast.AsyncFor, ast.While)):
                self._walk(mod, s.body, cls, fn, out)
                self._walk(mod, s.orelse, cls, fn, out)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                self._walk(mod, s.body, cls, fn, out)
            elif isinstance(s, ast.Try):
                self._walk(mod, s.body, cls, fn, out)
                for h in s.handlers:
                    self._walk(mod, h.body, cls, fn, out)
                self._walk(mod, s.orelse, cls, fn, out)
                self._walk(mod, s.finalbody, cls, fn, out)
            else:
                for n in ast.walk(s):
                    if _is_thread_ctor(n):
                        self._check_ctor(mod, s, n, cls, fn, out)

    def _check_ctor(
        self,
        mod: SourceModule,
        stmt: ast.stmt,
        ctor: ast.Call,
        cls: ast.ClassDef | None,
        fn: ast.FunctionDef | ast.AsyncFunctionDef | None,
        out: list,
    ) -> None:
        kw = {k.arg: k.value for k in ctor.keywords}
        if "name" not in kw:
            out.append(mod.finding(
                self.name, ctor,
                "thread constructed without name=: invisible in stack "
                "dumps — name it dllama-<role>",
            ))
        daemon = kw.get("daemon")
        if not (isinstance(daemon, ast.Constant) and daemon.value is True):
            out.append(mod.finding(
                self.name, ctor,
                "thread constructed without daemon=True: a leaked "
                "non-daemon thread turns interpreter shutdown into a hang",
            ))
        self._check_join_path(mod, stmt, ctor, cls, fn, out)

    def _check_join_path(
        self,
        mod: SourceModule,
        stmt: ast.stmt,
        ctor: ast.Call,
        cls: ast.ClassDef | None,
        fn: ast.FunctionDef | ast.AsyncFunctionDef | None,
        out: list,
    ) -> None:
        # binding: self.X = Thread(...) | x = Thread(...) — including the
        # list idiom x = [Thread(...) for ...] | Thread(...).start()
        if isinstance(stmt, ast.Assign) and any(
            n is ctor for n in ast.walk(stmt.value)
        ):
            target = stmt.targets[0]
            if is_self_attr(target) and cls is not None:
                if not _class_has_stop_path(cls, target.attr):
                    out.append(mod.finding(
                        self.name, ctor,
                        f"thread stored in self.{target.attr} but class "
                        f"{cls.name} has no stop/close/shutdown/join path",
                    ))
                return
            if isinstance(target, ast.Name) and fn is not None:
                if not _function_joins(fn, target.id):
                    out.append(mod.finding(
                        self.name, ctor,
                        f"thread bound to {target.id!r} is never joined in "
                        f"{fn.name}()",
                    ))
                return
        out.append(mod.finding(
            self.name, ctor,
            "fire-and-forget thread: no handle survives to join or stop "
            "it",
        ))
