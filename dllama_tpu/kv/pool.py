"""Host-side accounting for a fixed-size-page KV pool.

The pool tracks *page ids* into a device buffer owned by the engine
(``[n_layers, n_pages, n_kv_heads, page_size, head_dim]``); no device state
lives here.  Every page has a refcount:

- ``alloc()`` hands out pages at refcount 1 (the caller — in practice the
  radix tree — owns them),
- ``retain()``/``release()`` add/remove users (a lane adopting a shared
  prefix retains its pages for the life of the stream),
- a page whose refcount drops to 0 returns to the free list.

Page 0 is reserved as a scratch page: bucketed device copy programs pad
their page-id vectors with it, so it must never be handed to a caller.

``fork()`` is the copy-on-write bookkeeping step: when a stored prefix
diverges mid-page, the divergent stream gets a freshly allocated page (the
device copy happens in the engine) and the fork is counted for telemetry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

SCRATCH_PAGE = 0


@dataclass
class PoolStats:
    total: int          # usable pages (excludes the scratch page)
    free: int
    used: int
    shared: int         # pages with refcount >= 2 (tree + at least one lane)
    cow_forks: int


class PagePool:
    """Refcounted free-list allocator over ``n_pages`` fixed-size pages."""

    def __init__(
        self,
        n_pages: int,
        page_size: int,
        on_event: Optional[Callable[[str, dict], None]] = None,
    ) -> None:
        if n_pages < 2:
            raise ValueError(f"PagePool needs >= 2 pages (1 is scratch), got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = n_pages
        self.page_size = page_size
        self._refs: Dict[int, int] = {}
        # LIFO free list keeps recently-freed (still-warm) pages hot.
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._cow_forks = 0
        self._on_event = on_event

    # -- events ------------------------------------------------------------
    def _emit(self, kind: str, **payload) -> None:
        if self._on_event is not None:
            self._on_event(kind, payload)

    # -- allocation --------------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages at refcount 1. Raises MemoryError when short."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise MemoryError(f"pool exhausted: want {n} pages, {len(self._free)} free")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        if n:
            self._emit("kv_page_alloc", n=n, free=len(self._free))
        return pages

    def fork(self, src: int) -> int:
        """COW-fork accounting: allocate a private copy slot for ``src``."""
        if src == SCRATCH_PAGE:
            raise KeyError(f"fork of reserved scratch page {src}")
        if src not in self._refs:
            raise KeyError(f"fork of unallocated page {src}")
        page = self.alloc(1)[0]
        self._cow_forks += 1
        self._emit("kv_cow_fork", src=src, dst=page)
        return page

    def retain(self, pages: List[int]) -> None:
        for p in pages:
            if p not in self._refs:
                raise KeyError(f"retain of unallocated page {p}")
            self._refs[p] += 1

    def release(self, pages: List[int]) -> int:
        """Drop one ref per page; returns how many pages were freed."""
        freed = 0
        for p in pages:
            refs = self._refs.get(p)
            if refs is None:
                raise KeyError(f"release of unallocated page {p}")
            if refs == 1:
                del self._refs[p]
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = refs - 1
        if freed:
            self._emit("kv_page_free", n=freed, free=len(self._free))
        return freed

    # -- introspection -----------------------------------------------------
    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def stats(self) -> PoolStats:
        shared = sum(1 for r in self._refs.values() if r >= 2)
        return PoolStats(
            total=self.n_pages - 1,
            free=len(self._free),
            used=len(self._refs),
            shared=shared,
            cow_forks=self._cow_forks,
        )

    def check(self) -> None:
        """Invariant sweep — every page is exactly one of {scratch, free, allocated}."""
        seen = set(self._free)
        if len(seen) != len(self._free):
            raise AssertionError("free list contains duplicates")
        if SCRATCH_PAGE in seen or SCRATCH_PAGE in self._refs:
            raise AssertionError("scratch page leaked into free list / allocations")
        for p, r in self._refs.items():
            if p in seen:
                raise AssertionError(f"page {p} both free and allocated")
            if r < 1:
                raise AssertionError(f"page {p} has refcount {r}")
        n_accounted = 1 + len(self._free) + len(self._refs)
        if n_accounted != self.n_pages:
            raise AssertionError(
                f"page leak: scratch + {len(self._free)} free + "
                f"{len(self._refs)} allocated != {self.n_pages} total"
            )

    def reset(self) -> None:
        self._refs.clear()
        self._free = list(range(self.n_pages - 1, 0, -1))
