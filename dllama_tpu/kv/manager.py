"""PagedKVManager: glue between the page pool / radix tree and the engine.

Owns the host-side accounting for the engine's device page pool and the
serving-path policy around it:

- ``match``   — at admission, find the longest stored token prefix of the
  request's (fully retokenized) conversation and the pages covering it,
  retaining them for the lane on the spot (the scheduler runs the adopt
  copy a tick later; unpinned pages could be evicted and reallocated to
  another sequence in that gap);
- ``adopt``   — copy the matched pages into the admitted lane's slab
  (one bucketed device gather);
- ``publish`` — at finish, store the lane's fed tokens' whole pages back
  into the pool, deduplicating against the tree so a prefix two streams
  share is physically stored ONCE (the second publisher allocates pages
  only for its unshared suffix, forking copy-on-write at a mid-page
  divergence);
- ``release_lane`` / ``reset`` — refcount hygiene and the error path.

All engine calls are made by the scheduler thread; ``lock`` only protects
the host-side accounting against concurrent /v1/debug/kv and /metrics
readers.
"""

from __future__ import annotations

import logging
from typing import Any

from ..analysis.lockwatch import make_lock
from ..obs.metrics import get_registry
from ..obs.recorder import get_recorder
from ..obs.spans import get_span_tracker
from ..runtime.faults import InjectedFault, get_fault_plane
from .pool import PagePool
from .radix import RadixTree

logger = logging.getLogger(__name__)

DEFAULT_PAGE_SIZE = 16


class PagedKVManager:
    def __init__(
        self,
        engine: Any,
        page_size: int = 0,
        n_pages: int = 0,
        evict_counter: Any = None,
        native: bool = False,
    ) -> None:
        self.engine = engine
        self.page_size = page_size or DEFAULT_PAGE_SIZE
        # native mode (ISSUE 16): the pool is the lanes' only KV home —
        # adopt becomes refcount bumps + a page-table write (device copy
        # only for a COW mid-page boundary) and publish becomes ownership
        # transfer of pages the lane already wrote
        self.native = bool(native)
        n = engine.init_kv_pool(self.page_size, n_pages, native=self.native)
        self.recorder = get_recorder()
        # component="kv" spans over the host-side accounting (the engine's
        # device copies inside adopt/publish record their own spans)
        self.spans = get_span_tracker()
        self.pool = PagePool(n, self.page_size, on_event=self._pool_event)
        self.tree = RadixTree(self.page_size)
        self.lock = make_lock("kv.manager")
        self._lane_pages: dict[int, list[int]] = {}
        self._lane_match_tokens: dict[int, int] = {}
        # radix anchor of each lane's last match (runtime/spec.py shared
        # n-gram store): (node_id, matched token count), or absent when
        # the match found nothing
        self._lane_anchor: dict[int, tuple[int, int]] = {}
        # dashboards keep their dllama_cache_evictions_total series: the
        # ApiState hands us its handle and radix evictions feed it
        self._evict_counter = evict_counter
        obs = get_registry()
        self.g_total = obs.gauge(
            "dllama_kv_pages_total",
            "Usable pages in the shared KV pool (excludes the scratch page).",
        )
        self.g_free = obs.gauge(
            "dllama_kv_pages_free", "KV pool pages on the free list."
        )
        self.g_shared = obs.gauge(
            "dllama_kv_pages_shared",
            "KV pool pages referenced by the radix tree AND at least one "
            "live lane (refcount >= 2) — the physically-shared prefix "
            "storage.",
        )
        self.c_hits = obs.counter(
            "dllama_radix_hits_total",
            "Admissions whose conversation matched a stored radix prefix "
            "and adopted shared pages.",
        )
        self.c_evictions = obs.counter(
            "dllama_radix_evictions_total",
            "Pages LRU-evicted from radix-tree leaves to make room for a "
            "publish.",
        )
        self.c_shared_tokens = obs.counter(
            "dllama_shared_prefix_tokens_total",
            "Prompt tokens served from shared pool pages instead of being "
            "re-prefilled (sum of adopted prefix lengths).",
        )
        self.c_cow = obs.counter(
            "dllama_kv_cow_forks_total",
            "Copy-on-write page forks: a publish diverged mid-page from a "
            "stored prefix and took a private copy of that page slot.",
        )
        self.g_total.set(n - 1)
        self._update_gauges_locked()

    # -- internals ---------------------------------------------------------
    def _pool_event(self, kind: str, payload: dict) -> None:
        self.recorder.record(kind, **payload)
        if kind == "kv_cow_fork":
            self.c_cow.inc()

    def _update_gauges_locked(self) -> None:
        st = self.pool.stats()
        self.g_free.set(st.free)
        self.g_shared.set(st.shared)

    # -- admission ---------------------------------------------------------
    def match(self, lane: int, tokens: list[int]) -> tuple[int, list[int]]:
        """Longest reusable stored prefix of ``tokens``: returns
        ``(n_reused_tokens, pages)``. Reuse is capped one short of the
        prompt (the engine must be fed at least one token) and to the
        rows the collected pages actually cover; a partial final page is
        fine (its stale tail rows are overwritten by suffix prefill
        before any query can attend to them).

        The returned pages are retained for ``lane`` HERE, inside the
        lock: the scheduler runs the adopt copy one tick later, and
        another lane's publish->evict in that window could otherwise
        free and reallocate refcount-1 pages, silently handing the new
        lane a different sequence's KV. Every admission-failure path
        already funnels through :meth:`release_lane`, which drops the
        retain whether or not the adopt copy ever ran."""
        ps = self.page_size
        with self.spans.span(
            "kv_match", component="kv", lane=lane, n_tokens=len(tokens)
        ), self.lock:
            # a lane admitted twice without release would leak a retain
            stale = self._lane_pages.pop(lane, None)
            if stale:
                self.pool.release(stale)
            mr = self.tree.match(tokens)
            # the anchor follows the raw token match (not the page cap):
            # sibling grouping only needs prefix identity, not adoptable
            # KV — a lane can share an anchor with zero reusable pages
            if mr.anchor is not None:
                self._lane_anchor[lane] = (mr.anchor, mr.n_tokens)
            else:
                self._lane_anchor.pop(lane, None)
            m = min(mr.n_tokens, len(mr.pages) * ps, len(tokens) - 1)
            if m <= 0:
                self._lane_match_tokens[lane] = 0
                self._update_gauges_locked()
                return 0, []
            n_pages = -(-m // ps)  # ceil
            pages = mr.pages[:n_pages]
            self.pool.retain(pages)
            self._lane_pages[lane] = list(pages)
            self._lane_match_tokens[lane] = m
            self._update_gauges_locked()
            return m, pages

    def adopt(self, lane: int, pages: list[int]) -> None:
        """Slab mode: device-copy ``pages`` (already retained by
        :meth:`match`) into ``lane``'s slab. Native mode: build the lane's
        full page list — the shared prefix pages as-is, a COW fork of a
        mid-page boundary (the only device copy), and freshly allocated
        private pages for everything the lane will write — and point the
        engine's page table at it. A full-page prefix match therefore
        moves ZERO device bytes."""
        if not self.native:
            if pages:
                self.engine.kv_adopt(lane, pages)
            return
        self._adopt_native(lane, pages)

    def _adopt_native(self, lane: int, pages: list[int]) -> None:
        ps = self.page_size
        n_blocks = self.engine._kv_n_blocks
        with self.spans.span(
            "kv_adopt_native", component="kv", lane=lane, n_pages=len(pages)
        ), self.lock:
            fault = get_fault_plane().draw("kv_alloc", op="adopt")
            if fault is not None:
                raise fault
            m = self._lane_match_tokens.get(lane, 0)
            lane_list = list(pages)
            if m % ps and lane_list:
                # mid-page boundary: the lane will scatter rows >= m into
                # this slot, so it needs a private copy of the shared page
                orig = lane_list[-1]
                fork = self._alloc_lane_pages(1, lane, fork_src=orig)[0]
                try:
                    self.engine.kv_page_copy([orig], [fork])
                except BaseException:
                    self.pool.release([fork])
                    raise
                # swap the lane's retain from the shared original to the
                # private fork (the tree keeps its own ref on the original)
                self.pool.release([orig])
                lane_list[-1] = fork
            need = n_blocks - len(lane_list)
            if need > 0:
                lane_list += self._alloc_lane_pages(need, lane)
            self._lane_pages[lane] = lane_list
            self.engine.adopt_pages(lane, lane_list)
            self._update_gauges_locked()

    def _alloc_lane_pages(
        self, n: int, lane: int, fork_src: int | None = None
    ) -> list[int]:
        """Allocate ``n`` private pages for a native lane, LRU-evicting
        refcount-1 tree leaves under pressure. Unlike the publish path a
        shortfall here RAISES (MemoryError): admission cannot proceed
        without somewhere to write, and the scheduler's retry/fail path
        already handles a transient adopt failure."""
        short = n - self.pool.free_pages
        if short > 0:
            freed = self.tree.evict(short, self.pool)  # dlint: disable=guarded-attrs — only called from _adopt_native, under self.lock
            self.c_evictions.inc(freed)
            if self._evict_counter is not None:
                self._evict_counter.inc(freed)
            if freed:
                self.recorder.record("kv_evict", n_pages=freed, lane=lane)
        if fork_src is not None:
            return [self.pool.fork(fork_src)]
        return self.pool.alloc(n)

    def anchor_for(self, lane: int) -> tuple[int, int] | None:
        """(radix node_id, matched token count) of ``lane``'s last
        :meth:`match`, or None when nothing matched — the grouping key
        the scheduler hands the shared n-gram drafter."""
        with self.lock:
            return self._lane_anchor.get(lane)

    def release_lane(self, lane: int) -> None:
        with self.lock:
            pages = self._lane_pages.pop(lane, None)
            self._lane_match_tokens.pop(lane, None)
            self._lane_anchor.pop(lane, None)
            if pages:
                self.pool.release(pages)
            if self.native:
                self.engine.clear_lane_pages(lane)
            self._update_gauges_locked()

    # -- finish ------------------------------------------------------------
    def publish(self, lane: int, tokens: list[int]) -> int:
        """Store ``lane``'s fed ``tokens`` (KV rows [0, len(tokens)) are
        live in its slab) as whole pages. Dedups against the tree first:
        slots the tree already holds are NOT copied again — that is what
        makes a fanned-out system prompt physically one set of pages.
        Returns the number of pages newly stored (0 = full dedup or no
        whole page to store)."""
        with self.spans.span(
            "kv_publish_host", component="kv", lane=lane,
            n_tokens=len(tokens),
        ):
            return self._publish(lane, tokens)

    def _publish(self, lane: int, tokens: list[int]) -> int:
        if self.native:
            return self._publish_native(lane, tokens)
        ps = self.page_size
        n_full = len(tokens) // ps
        if n_full == 0:
            return 0
        full = list(tokens[: n_full * ps])
        with self.lock:
            mr = self.tree.match(full)
            k_shared = min(mr.n_tokens // ps, n_full)
            n_new = n_full - k_shared
            if n_new == 0:
                return 0
            # Pin the matched prefix across the eviction: under pool
            # pressure the matched leaf itself can be the refcount-1 LRU
            # victim, which would leave ``mr``/``k_shared`` pointing at
            # freed (possibly reallocated) pages and the insert below
            # rebuilding a token path with no pages behind its lower
            # slots. Pinned pages are refcount >= 2 and unevictable.
            self.pool.retain(mr.pages)
            try:
                short = n_new - self.pool.free_pages
                if short > 0:
                    freed = self.tree.evict(short, self.pool)
                    self.c_evictions.inc(freed)
                    if self._evict_counter is not None:
                        self._evict_counter.inc(freed)
                    if freed:
                        self.recorder.record(
                            "kv_evict", n_pages=freed, lane=lane
                        )
                if n_new > self.pool.free_pages:
                    # pool is full of retained/live pages: skip publishing
                    # rather than stall (the stream already served; only
                    # future reuse is lost)
                    self.recorder.record(
                        "kv_publish_skipped", lane=lane, want=n_new,
                        free=self.pool.free_pages,
                    )
                    return 0
                diverged_mid_page = (
                    mr.n_tokens > k_shared * ps and len(mr.pages) > k_shared
                )
                fork_page = mr.pages[k_shared] if diverged_mid_page else None
                pages = self._alloc_publish_pages(fork_page, n_new, lane)
            finally:
                self.pool.release(mr.pages)
            if pages is None:
                return 0
        pool_epoch0 = getattr(self.engine, "kv_pool_epoch", 0)
        try:
            self.engine.kv_publish(lane, pages, start_page=k_shared)
        except BaseException:
            if getattr(self.engine, "kv_pool_epoch", 0) != pool_epoch0:
                # the publish program donated the pool buffer and the
                # engine guard rebuilt it: EVERY page's device contents
                # are gone, so drop all host accounting with them
                logger.exception("kv_publish poisoned the pool; resetting")
                self.reset(reset_device=False)
            else:
                # transient failure before the buffer was touched (e.g.
                # an injected dispatch fault): only this publish's fresh
                # pages are suspect — release them and keep every
                # survivor's pages and the stored prefixes intact
                logger.exception(
                    "kv_publish failed; dropping this publish's pages"
                )
                with self.lock:
                    self.pool.release(pages)
                    self._update_gauges_locked()
            return 0
        with self.lock:
            try:
                self.tree.insert(full, pages, first_slot=k_shared)
            except Exception:
                # insert validates that dedup'd slots still exist on the
                # stored path; a rejection means the accounting raced —
                # drop the new pages and skip the store instead of
                # crashing the scheduler (only future reuse is lost)
                logger.exception("kv radix insert rejected; publish dropped")
                self.pool.release(pages)
                self._update_gauges_locked()
                return 0
            self._update_gauges_locked()
        return n_new

    def _publish_native(self, lane: int, tokens: list[int]) -> int:
        """Native publish = ownership transfer, zero device work: the
        lane already WROTE its KV into its private pool pages, so storing
        a prefix means retaining those pages for the tree and inserting
        the token path. Dedup still applies: slots the tree already holds
        keep the tree's pages (the lane's duplicates are freed at
        release_lane)."""
        ps = self.page_size
        n_full = len(tokens) // ps
        if n_full == 0:
            return 0
        full = list(tokens[: n_full * ps])
        fault = get_fault_plane().draw("dispatch", op="kv_publish")
        if fault is not None:
            # degraded-not-dead, same policy as the slab skip paths: the
            # stream already served, only future reuse is lost
            self.recorder.record(
                "kv_publish_skipped", lane=lane, want=n_full, error=str(fault)
            )
            return 0
        with self.lock:
            lane_list = self._lane_pages.get(lane) or []
            if len(lane_list) < n_full:
                return 0
            mr = self.tree.match(full)
            k_shared = min(mr.n_tokens // ps, n_full)
            n_new = n_full - k_shared
            if n_new == 0:
                return 0
            pages = lane_list[k_shared:n_full]
            # the tree must own its own reference BEFORE insert: the
            # lane's retain dies with release_lane, and a tree pointing
            # at freed pages would hand later admissions recycled KV
            self.pool.retain(pages)
            try:
                self.tree.insert(full, pages, first_slot=k_shared)
            except Exception:
                logger.exception("kv radix insert rejected; publish dropped")
                self.pool.release(pages)
                self._update_gauges_locked()
                return 0
            self._update_gauges_locked()
        return n_new

    def _alloc_publish_pages(
        self, fork_page: int | None, n_new: int, lane: int
    ) -> list[int] | None:
        """Allocate ``n_new`` pages for a publish, copy-on-write-forking
        ``fork_page`` as the first when the stored prefix diverged
        mid-page. Returns None on allocation failure (or an injected
        ``kv_alloc`` fault) — survivable by design: the stream already
        served, only future reuse is lost (same degraded-not-dead policy
        as the full-pool publish skip)."""
        try:
            fault = get_fault_plane().draw("kv_alloc", op="publish")
            if fault is not None:
                raise fault
            if fork_page is None:
                return self.pool.alloc(n_new)
            rest = self.pool.alloc(n_new - 1)
            try:
                return [self.pool.fork(fork_page)] + rest
            except MemoryError:
                self.pool.release(rest)
                raise
        except (MemoryError, InjectedFault) as e:
            self.recorder.record(
                "kv_alloc_failed", lane=lane, want=n_new, error=str(e)
            )
            return None

    def note_hit(self, n_tokens: int) -> None:
        self.c_hits.inc()
        self.c_shared_tokens.inc(n_tokens)

    # -- error path / introspection ----------------------------------------
    def reset(self, reset_device: bool = True) -> None:
        """Drop every page and stored prefix (host and, by default, the
        device buffer) — the big hammer for engine-error recovery paths
        that cannot trust pool contents."""
        with self.lock:
            self.tree.clear()
            self.pool.reset()
            self._lane_pages.clear()
            self._lane_match_tokens.clear()
            self._lane_anchor.clear()
            if self.native:
                self.engine.clear_all_lane_pages()
            self._update_gauges_locked()
        if reset_device:
            self.engine.reset_kv_pool()
        self.recorder.record("kv_pool_reset")

    def release_all_lanes(self) -> None:
        """Scheduler-error path: every lane was dropped, release their
        retains. Pool pages themselves are NOT donated by decode/prefill
        dispatches, so the tree's stored prefixes stay valid."""
        with self.lock:
            for pages in self._lane_pages.values():
                self.pool.release(pages)
            self._lane_pages.clear()
            self._lane_match_tokens.clear()
            self._lane_anchor.clear()
            if self.native:
                self.engine.clear_all_lane_pages()
            self._update_gauges_locked()

    def check(self) -> None:
        with self.lock:
            self.pool.check()

    def debug(self) -> dict:
        """The /v1/debug/kv payload."""
        with self.lock:
            st = self.pool.stats()
            return {
                "page_size": self.page_size,
                "pool": {
                    "total": st.total,
                    "free": st.free,
                    "used": st.used,
                    "shared": st.shared,
                    "cow_forks": st.cow_forks,
                },
                "radix": {
                    "nodes": self.tree.node_count(),
                    "tokens": self.tree.token_count(),
                    "pages": self.tree.n_pages,
                },
                "lanes": {
                    str(lane): len(pages)
                    for lane, pages in self._lane_pages.items()
                },
            }
