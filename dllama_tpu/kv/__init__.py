"""Paged KV subsystem: fixed-size page pool + radix prefix tree.

``pool``   — host-side page accounting (refcounts, free list, COW forks).
``radix``  — prefix tree over token IDs mapping shared prefixes to page slots.
``manager``— glue between the pool/tree, the engine's device page buffer, and
             the lane scheduler (adopt at admission, publish at finish).
"""

from dllama_tpu.kv.pool import PagePool, PoolStats
from dllama_tpu.kv.radix import MatchResult, RadixTree

__all__ = ["PagePool", "PoolStats", "RadixTree", "MatchResult"]
