"""Radix (compressed prefix) tree over token IDs mapping prefixes to KV pages.

Stored sequences are always truncated to whole pages (``n * page_size``
tokens), so every page *slot* ``s`` covers token positions
``[s*ps, (s+1)*ps)``.  A page is owned by the unique tree node whose edge
span contains the slot's **last** position — two sequences share slot ``s``
iff they agree on all tokens through ``(s+1)*ps``, which is exactly the
condition under which their KV rows for that slot are identical (causal
attention: row ``p`` depends only on tokens ``[0, p]``).  This rule keeps
the pages collected while descending consecutive from slot 0.

``match`` is token-granular: the caller may reuse a *partial* final page
(rows past the match point hold stale tokens but are overwritten by suffix
prefill before any query position can attend to them — the same argument
that makes the engine's parked-lane padding rows safe).

Eviction is LRU over leaves whose pages have no users beyond the tree
itself (refcount 1 in the :class:`~dllama_tpu.kv.pool.PagePool`).

**Node identity** (the shared-speculation anchor, runtime/spec.py): every
node carries a monotonically assigned ``node_id``, and :meth:`match`
reports the id of the deepest node whose edge contributed at least one
matched token as ``MatchResult.anchor``.  When an edge is SPLIT the new
head — the node that keeps the shared prefix — INHERITS the original id
and the tail gets a fresh one, so streams that anchored on a prefix stay
grouped under one id even after later inserts carve the edge up.  Ids are
advisory grouping keys only: eviction retires them silently (the shared
n-gram store ages the group out by LRU), and every draft they seed is
verified, so a stale anchor can cost acceptance but never correctness.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

if TYPE_CHECKING:
    from .pool import PagePool


@dataclass
class MatchResult:
    n_tokens: int                 # longest stored prefix agreeing with the query
    pages: List[int]              # page ids for slots 0..len(pages)-1, in slot order
    # pages may extend past n_tokens (stale tail rows — safe to adopt) and is
    # always consecutive from slot 0.
    anchor: Optional[int] = None  # node_id of the deepest edge that matched
    # (None when nothing matched — the root is never an anchor)


class _Node:
    __slots__ = (
        "tokens", "start", "children", "pages", "parent", "last_access",
        "node_id",
    )

    def __init__(
        self,
        tokens: Tuple[int, ...],
        start: int,
        parent: Optional["_Node"],
        node_id: int = 0,
    ) -> None:
        self.tokens = tokens          # edge label from parent
        self.start = start            # absolute position of tokens[0]
        self.children: Dict[int, _Node] = {}
        self.pages: List[Tuple[int, int]] = []   # (slot, page_id), slot-ascending
        self.parent = parent
        self.last_access = 0
        self.node_id = node_id        # stable grouping key (see module doc)

    @property
    def end(self) -> int:
        return self.start + len(self.tokens)


class RadixTree:
    def __init__(self, page_size: int) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.root = _Node((), 0, None, node_id=0)
        self._clock = 0
        self._n_pages = 0
        self._next_id = 1

    # -- helpers -----------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _new_id(self) -> int:
        nid = self._next_id
        self._next_id += 1
        return nid

    def _slot_end(self, slot: int) -> int:
        return (slot + 1) * self.page_size - 1

    # -- lookup ------------------------------------------------------------
    def match(self, tokens: Sequence[int], touch: bool = True) -> MatchResult:
        """Longest stored prefix of ``tokens`` plus the pages covering it."""
        now = self._tick() if touch else self._clock
        node = self.root
        matched = 0
        pages: List[int] = []
        anchor: Optional[int] = None
        while True:
            if touch:
                node.last_access = now
            if matched >= len(tokens):
                break
            child = node.children.get(tokens[matched])
            if child is None:
                break
            edge = child.tokens
            j = 0
            limit = min(len(edge), len(tokens) - matched)
            while j < limit and edge[j] == tokens[matched + j]:
                j += 1
            if j > 0:
                # Every page on ``child`` continues the consecutive slot run;
                # pages past the agreement point only carry stale tail rows.
                pages.extend(pid for _, pid in child.pages)
                matched += j
                # deepest edge with >= 1 agreeing token: a PARTIAL edge
                # match still anchors here — when the diverging stream
                # later publishes, the split head inherits this very id
                anchor = child.node_id
                if touch:
                    child.last_access = now
            if j < len(edge):
                break
            node = child
        return MatchResult(n_tokens=matched, pages=pages, anchor=anchor)

    # -- insertion ---------------------------------------------------------
    def insert(
        self,
        tokens: Sequence[int],
        new_pages: Sequence[int],
        first_slot: int,
    ) -> None:
        """Store ``tokens`` (must be whole pages), attaching ``new_pages`` to
        slots ``first_slot .. first_slot+len(new_pages)-1``.  Slots below
        ``first_slot`` must already be present along the matched path (the
        caller dedups via :meth:`match` first)."""
        ps = self.page_size
        if len(tokens) % ps != 0:
            raise ValueError(f"insert length {len(tokens)} not a multiple of page_size {ps}")
        n_full = len(tokens) // ps
        if first_slot + len(new_pages) != n_full:
            raise ValueError(
                f"pages for slots [{first_slot}, {first_slot + len(new_pages)}) "
                f"do not reach sequence end (slot {n_full})"
            )
        if first_slot > 0:
            # Slots the caller dedup'd away must actually be stored: an
            # insert whose lower slots are missing (e.g. the matched leaf
            # was evicted after the caller's match) would otherwise build
            # a token path whose early positions have NO pages behind
            # them, and later matches would hand out the suffix pages as
            # if they covered slot 0. Validate BEFORE any mutation.
            need = first_slot * ps
            covered: set = set()
            node, pos = self.root, 0
            while pos < need:
                child = node.children.get(tokens[pos])
                if child is None:
                    break
                edge = child.tokens
                j = 0
                limit = min(len(edge), len(tokens) - pos)
                while j < limit and edge[j] == tokens[pos + j]:
                    j += 1
                if j > 0:
                    covered.update(s for s, _ in child.pages)
                pos += j
                if j < len(edge):
                    break
                node = child
            missing = set(range(first_slot)) - covered
            if pos < need or missing:
                raise ValueError(
                    f"insert(first_slot={first_slot}) on a path storing "
                    f"only {pos} matching tokens, missing page slots "
                    f"{sorted(missing)} — dedup'd slots must already "
                    "exist on the matched path"
                )
        now = self._tick()
        node = self.root
        pos = 0
        while pos < len(tokens):
            node.last_access = now
            child = node.children.get(tokens[pos])
            if child is None:
                child = _Node(tuple(tokens[pos:]), pos, node, self._new_id())
                node.children[tokens[pos]] = child
                child.last_access = now
                node = child
                pos = len(tokens)
                break
            edge = child.tokens
            j = 0
            limit = min(len(edge), len(tokens) - pos)
            while j < limit and edge[j] == tokens[pos + j]:
                j += 1
            if j < len(edge):
                # Split child's edge at offset j; ``head`` is the new parent
                # holding the shared prefix of the edge.
                head = self._split(child, j)
                head.last_access = now
                if j < len(tokens) - pos:
                    # Diverged: hang the remaining suffix off the split point.
                    rest = _Node(
                        tuple(tokens[pos + j:]), pos + j, head, self._new_id()
                    )
                    head.children[tokens[pos + j]] = rest
                    rest.last_access = now
                pos = len(tokens)
                break
            child.last_access = now
            node = child
            pos += j
        # Attach each new page to the node containing its slot's last position.
        for i, pid in enumerate(new_pages):
            slot = first_slot + i
            owner = self._node_at(tokens, self._slot_end(slot))
            owner.pages.append((slot, pid))
            owner.pages.sort()
        self._n_pages += len(new_pages)

    def _split(self, node: _Node, offset: int) -> "_Node":
        """Split ``node``'s edge at ``offset``: node keeps the tail, a new
        parent takes the head (and the pages whose slots end in it).  The
        head INHERITS ``node``'s id — streams that anchored on this edge
        matched at least its head, so the grouping key must follow the
        shared prefix; the tail is a new, narrower identity."""
        assert 0 < offset < len(node.tokens)
        head = _Node(node.tokens[:offset], node.start, node.parent, node.node_id)
        node.node_id = self._new_id()
        head.last_access = node.last_access
        node.parent.children[node.tokens[0]] = head
        node.parent = head
        node.start += offset
        node.tokens = node.tokens[offset:]
        head.children[node.tokens[0]] = node
        keep, move = [], []
        for slot, pid in node.pages:
            (move if self._slot_end(slot) < node.start else keep).append((slot, pid))
        node.pages = keep
        head.pages = move
        return head

    def _node_at(self, tokens: Sequence[int], position: int) -> _Node:
        """Node whose edge span contains absolute ``position`` along ``tokens``."""
        node = self.root
        while True:
            child = node.children[tokens[node.end]]
            if child.end > position:
                return child
            node = child

    # -- eviction ----------------------------------------------------------
    def evict(self, n_pages: int, pool: "PagePool") -> int:
        """LRU-evict leaves whose pages only the tree holds (refcount 1),
        releasing them into ``pool`` until ``n_pages`` are freed or nothing
        is evictable.  Returns pages freed."""
        freed = 0
        while freed < n_pages:
            victim: Optional[_Node] = None
            stack = [self.root]
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                    continue
                if node is self.root:
                    continue
                if any(pool.refcount(pid) != 1 for _, pid in node.pages):
                    continue
                if victim is None or node.last_access < victim.last_access:
                    victim = node
            if victim is None:
                break
            freed += pool.release([pid for _, pid in victim.pages])
            self._n_pages -= len(victim.pages)
            parent = victim.parent
            del parent.children[victim.tokens[0]]
            # Collapse now-childless, pageless ancestors immediately:
            # left in place they are match()-able token spans with no
            # pages behind them, inflating node/token counts forever if
            # pressure never recurs.
            node = parent
            while node is not self.root and not node.children and not node.pages:
                del node.parent.children[node.tokens[0]]
                node = node.parent
        return freed

    # -- introspection -----------------------------------------------------
    @property
    def n_pages(self) -> int:
        return self._n_pages

    def node_count(self) -> int:
        n, stack = 0, [self.root]
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n - 1  # exclude root

    def token_count(self) -> int:
        n, stack = 0, [self.root]
        while stack:
            node = stack.pop()
            n += len(node.tokens)
            stack.extend(node.children.values())
        return n

    def all_pages(self) -> List[int]:
        out, stack = [], [self.root]
        while stack:
            node = stack.pop()
            out.extend(pid for _, pid in node.pages)
            stack.extend(node.children.values())
        return out

    def clear(self, pool: Optional["PagePool"] = None) -> None:
        if pool is not None:
            pages = self.all_pages()
            if pages:
                pool.release(pages)
        self.root = _Node((), 0, None)
        self._n_pages = 0
