"""Span timeline tracing: where inside the engine each millisecond went.

PR 2's request tracer answers "how did this request do" with ONE flat
record; this layer answers "where did its time GO" with a timeline of
sub-request spans — queue wait, admission chunks, ``kv_adopt``/
``kv_publish`` copies, decode-block dispatch vs. device completion, SSE
flushes — each tagged with the owning request and lane so the full
serving path of one request reconstructs from a single export.

Design constraints mirror the metrics registry:

* **Low overhead.** A span is two ``perf_counter`` reads and one dict
  append under a short lock; with the tracker disabled ``begin`` returns
  ``None`` after one attribute read and ``end(None)`` is a no-op, so the
  bench's obs on/off comparison toggles this layer together with the
  registry and the recorder.
* **Bounded memory.** Completed spans land in a ring; old spans fall
  off. Drops are themselves observable: the first drop (and then every
  ``capacity`` further drops) records an ``obs_overflow`` flight-recorder
  event.
* **Two exports.** :meth:`SpanTracker.chrome_trace` renders the ring (or
  one request's spans) as Chrome-trace / Perfetto JSON — ``pid`` is the
  component (scheduler / engine / kv / http), ``tid`` is the lane — and
  :meth:`SpanTracker.request_summary` folds one request's spans into a
  millisecond accounting ("TTFT = 480ms: 210 queue + 190 prefill-chunks
  + 45 adopt + 35 first block") plus a wall-time coverage fraction.
  ``GET /v1/debug/timeline`` and ``--timeline-out`` serve both.

Threading: ``begin``/``end`` may run on different threads (the queue
span begins on the HTTP handler thread and ends on the scheduler
thread); a handle is mutated only by its ender and ``end`` is idempotent
(the first ender wins), so cross-thread handoff needs no lock beyond the
ring append.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Iterator

from .recorder import get_recorder

DEFAULT_CAPACITY = 4096

# stable Chrome-trace pid per component (new components get the next id)
_COMPONENT_PIDS = {"scheduler": 1, "engine": 2, "kv": 3, "http": 4, "cli": 5}

# Replica attribution (ISSUE 19): the in-process fleet shares ONE global
# tracker across N replicas, so span records carry the replica that
# produced them. The tag is registered per-thread (scheduler loop + HTTP
# handler threads are replica-owned; engine compile/prefetch helpers stay
# untagged) and stamped at ``begin`` — a span that begins on a replica
# thread and ends elsewhere keeps its origin.
_thread_ctx = threading.local()


def set_thread_replica(tag: str | None) -> None:
    """Tag every span subsequently begun on THIS thread with a replica
    name (``None`` clears). Single-replica servers never call this and
    their span records are unchanged."""
    _thread_ctx.replica = tag


def get_thread_replica() -> str | None:
    return getattr(_thread_ctx, "replica", None)


class _SpanHandle:
    """In-flight span state between ``begin`` and ``end``."""

    __slots__ = ("name", "component", "request_id", "lane", "t0", "attrs",
                 "replica", "done")

    def __init__(self, name, component, request_id, lane, t0, attrs,
                 replica=None):
        self.name = name
        self.component = component
        self.request_id = request_id
        self.lane = lane
        self.t0 = t0
        self.attrs = attrs
        self.replica = replica
        self.done = False


class SpanTracker:
    """Thread-safe bounded ring of completed spans; see module docstring."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        enabled: bool | None = None,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
        recorder: object | None = None,
    ) -> None:
        self.enabled = (
            enabled
            if enabled is not None
            else os.environ.get("DLLAMA_OBS", "1") != "0"
        )
        self.capacity = capacity
        self._clock = clock
        self._epoch = clock()  # all span t0s are seconds since this anchor
        self.epoch_unix = wall_clock()
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._recorder = recorder
        self._total = 0
        self._dropped = 0
        # optional throttled file sink (--timeline-out on the server)
        self._sink_path: str | None = None
        self._sink_min_interval = 5.0
        self._sink_last = 0.0

    @property
    def recorder(self):
        if self._recorder is None:
            self._recorder = get_recorder()
        return self._recorder

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # -- span lifecycle ----------------------------------------------------

    def begin(self, name: str, component: str = "engine",
              request_id: str | None = None, lane: int | None = None,
              **attrs) -> _SpanHandle | None:
        """Open a span; returns an opaque handle (or None when disabled —
        ``end(None)`` no-ops, so call sites never branch)."""
        if not self.enabled:
            return None
        return _SpanHandle(
            name, component, request_id, lane, self._clock(), attrs or None,
            replica=get_thread_replica(),
        )

    def end(self, handle: _SpanHandle | None, **attrs) -> None:
        """Close a span and commit it to the ring; idempotent (a second
        end — e.g. an error path racing the normal one — no-ops)."""
        if handle is None or handle.done:
            return
        handle.done = True
        t1 = self._clock()
        if attrs:
            handle.attrs = {**(handle.attrs or {}), **attrs}
        rec = {
            "name": handle.name,
            "component": handle.component,
            "request_id": handle.request_id,
            "lane": handle.lane,
            "t0": handle.t0 - self._epoch,
            "dur_s": max(t1 - handle.t0, 0.0),
        }
        if handle.replica is not None:
            rec["replica"] = handle.replica
        if handle.attrs:
            rec["attrs"] = handle.attrs
        overflowed = False
        with self._lock:
            self._total += 1
            if len(self._ring) == self.capacity:
                self._dropped += 1
                # rate-limit the meta-event: first drop, then every
                # `capacity` further drops (a busy server overflows on
                # every span once the ring is full)
                overflowed = self._dropped % self.capacity == 1
            self._ring.append(rec)
            dropped = self._dropped
        if overflowed:
            self.recorder.record(
                "obs_overflow", what="span_ring", capacity=self.capacity,
                dropped=dropped,
            )

    @contextmanager
    def span(self, name: str, component: str = "engine",
             request_id: str | None = None, lane: int | None = None,
             **attrs) -> Iterator[_SpanHandle]:
        """``with tracker.span("admission_chunk", ...):`` — the body is
        timed even when it raises (the error still took the time)."""
        handle = self.begin(name, component, request_id, lane, **attrs)
        try:
            yield handle
        finally:
            self.end(handle)

    # -- views -------------------------------------------------------------

    def completed(self, request_id: str | None = None,
                  replica: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._ring)
        if request_id is not None:
            spans = [s for s in spans if s["request_id"] == request_id]
        if replica is not None:
            spans = [s for s in spans if s.get("replica") == replica]
        return spans

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- Chrome-trace / Perfetto export ------------------------------------

    def chrome_trace(self, request_id: str | None = None,
                     replica: str | None = None,
                     pid_prefix: str | None = None,
                     pid_base: int = 0) -> dict:
        """Chrome-trace JSON-object format (loadable by Perfetto and
        chrome://tracing): one complete ("X") event per span, pid =
        component, tid = lane (-1 = no lane), ts/dur in microseconds
        since the tracker epoch. Extra top-level keys (the per-request
        summary under "dllama") are legal metadata both viewers ignore.

        ``replica`` keeps only spans stamped with that replica tag (the
        in-process fleet shares one tracker). ``pid_prefix`` prefixes
        every process name and ``pid_base`` offsets every pid, so a fleet
        stitcher can merge N fragments without two replicas' identical
        component names/pids colliding in the viewer (ISSUE 19)."""
        spans = self.completed(request_id, replica)
        events: list[dict] = []
        seen_pids: dict[str, int] = {}
        seen_tids: set[tuple[int, int]] = set()
        for s in spans:
            comp = s["component"]
            pid = _COMPONENT_PIDS.get(comp)
            if pid is None:
                pid = _COMPONENT_PIDS.setdefault(
                    comp, max(_COMPONENT_PIDS.values()) + 1
                )
            pid += pid_base
            tid = s["lane"] if s["lane"] is not None else -1
            if comp not in seen_pids:
                seen_pids[comp] = pid
                events.append({
                    "ph": "M", "pid": pid, "tid": 0,
                    "name": "process_name",
                    "args": {
                        "name": f"{pid_prefix}/{comp}" if pid_prefix
                        else comp
                    },
                })
            if (pid, tid) not in seen_tids:
                seen_tids.add((pid, tid))
                events.append({
                    "ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name",
                    "args": {
                        "name": f"lane {tid}" if tid >= 0 else "no lane"
                    },
                })
            args = {
                "request_id": s["request_id"],
                **(s.get("attrs") or {}),
            }
            if s.get("replica") is not None:
                args["replica"] = s["replica"]
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": round(s["t0"] * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "name": s["name"],
                "args": args,
            }
            events.append(ev)
        out = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "dllama": {
                "epoch_unix": self.epoch_unix,
                "n_spans": len(spans),
                "dropped": self.dropped,
            },
        }
        if replica is not None:
            out["dllama"]["replica"] = replica
        if request_id is not None:
            out["dllama"]["request_id"] = request_id
            out["dllama"]["summary"] = self.request_summary(request_id)
        return out

    def export_file(self, path: str, request_id: str | None = None) -> int:
        """Write the Chrome-trace JSON to ``path`` (``--timeline-out``);
        returns the span count. Serialization failures fall back to
        ``repr`` per value (same policy as the tracer sink)."""
        trace = self.chrome_trace(request_id)
        with open(path, "w") as f:
            f.write(json.dumps(trace, default=repr))
        return trace["dllama"]["n_spans"]

    def set_sink(self, path: str | None,
                 min_interval_s: float = 5.0) -> None:
        """Throttled auto-export: ``maybe_flush`` rewrites ``path`` at
        most every ``min_interval_s`` (the server calls it per finished
        request); ``flush`` writes unconditionally (server shutdown)."""
        self._sink_path = path
        self._sink_min_interval = min_interval_s
        self._sink_last = 0.0

    def maybe_flush(self) -> None:
        if self._sink_path is None:
            return
        now = self._clock()
        if now - self._sink_last < self._sink_min_interval:
            return
        self._sink_last = now
        self.flush()

    def flush(self) -> None:
        if self._sink_path is None:
            return
        try:
            self.export_file(self._sink_path)
        except OSError:
            self.recorder.record(
                "obs_sink_error", what="timeline", path=self._sink_path
            )

    # -- per-request millisecond accounting --------------------------------

    def request_summary(self, request_id: str) -> dict:
        """Fold one request's spans into per-phase totals and shares plus
        a wall-time coverage fraction (union of span intervals / first
        span start -> last span end). The ≥95%-coverage acceptance bar
        lives on this number: every serving phase is spanned, so the only
        uncovered time is scheduler-tick bookkeeping between spans."""
        spans = self.completed(request_id)
        if not spans:
            return {"request_id": request_id, "n_spans": 0, "phases": {},
                    "wall_ms": 0.0, "coverage": None}
        intervals = sorted(
            (s["t0"], s["t0"] + s["dur_s"]) for s in spans
        )
        wall_t0 = intervals[0][0]
        wall_t1 = max(t1 for _, t1 in intervals)
        wall = max(wall_t1 - wall_t0, 0.0)
        covered = 0.0
        cur0, cur1 = intervals[0]
        for t0, t1 in intervals[1:]:
            if t0 > cur1:
                covered += cur1 - cur0
                cur0, cur1 = t0, t1
            else:
                cur1 = max(cur1, t1)
        covered += cur1 - cur0
        phases: dict[str, dict] = {}
        for s in spans:
            ph = phases.setdefault(
                s["name"], {"n": 0, "total_ms": 0.0, "share": 0.0}
            )
            ph["n"] += 1
            ph["total_ms"] += s["dur_s"] * 1000.0
        for ph in phases.values():
            ph["total_ms"] = round(ph["total_ms"], 3)
            ph["share"] = (
                round(ph["total_ms"] / (wall * 1000.0), 4) if wall else None
            )
        return {
            "request_id": request_id,
            "n_spans": len(spans),
            "wall_ms": round(wall * 1000.0, 3),
            "covered_ms": round(covered * 1000.0, 3),
            "coverage": round(covered / wall, 4) if wall else None,
            "phases": dict(sorted(phases.items())),
        }


_DEFAULT = SpanTracker(
    capacity=int(os.environ.get("DLLAMA_SPAN_CAPACITY",
                                str(DEFAULT_CAPACITY))),
)


def get_span_tracker() -> SpanTracker:
    """The process-wide default span tracker (shared by the engine, the
    lane scheduler, the KV manager and ``/v1/debug/timeline``)."""
    return _DEFAULT
