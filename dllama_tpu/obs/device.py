"""Device memory telemetry: what HBM *actually* holds, per chip.

`utils/telemetry.memory_report` is the ANALYTIC accounting (bytes the
param/cache pytrees should occupy, divided by the sharding layout); this
module reads the runtime's own ledger via ``device.memory_stats()`` so
creeping allocations (a leaked donated buffer, an unexpected replication,
compile scratch that never freed) show up as a divergence instead of an
OOM three hours into a serving run.

``memory_stats()`` is a PJRT-optional surface: TPU backends report
``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``; the CPU
backend (and older jaxlibs) return None or raise — both degrade here to
an explicit ``available: false`` marker, never an exception, so the same
code path serves the CPU test backend and silicon.
"""

from __future__ import annotations

import logging

import jax

from .metrics import get_registry

logger = logging.getLogger(__name__)

# analytic-vs-measured divergence beyond this fraction logs a warning
DIVERGENCE_WARN_FRACTION = 0.10


def device_memory_stats() -> list[dict]:
    """Per-device memory snapshot; one entry per ``jax.devices()`` device.
    Entries carry ``available: False`` when the backend has no stats
    (CPU, or a PJRT plugin without the surface)."""
    out = []
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        entry = {
            "device": str(d),
            "platform": getattr(d, "platform", "unknown"),
            "available": stats is not None,
        }
        if stats is not None:
            entry["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            entry["peak_bytes_in_use"] = int(
                stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
            )
            entry["bytes_limit"] = int(stats.get("bytes_limit", 0))
        out.append(entry)
    return out


def sample_device_memory(registry: object | None = None) -> list[dict]:
    """Snapshot ``device_memory_stats()`` into registry gauges
    (``dllama_device_bytes_in_use`` / ``_peak_bytes_in_use`` /
    ``_bytes_limit``, labeled by device) and return the snapshot. On a
    stats-less backend the gauges are simply never set."""
    reg = registry if registry is not None else get_registry()
    g_use = reg.gauge(
        "dllama_device_bytes_in_use",
        "Device (HBM) bytes currently allocated, per chip "
        "(device.memory_stats; absent on backends without the surface).",
        labelnames=("device",),
    )
    g_peak = reg.gauge(
        "dllama_device_peak_bytes_in_use",
        "High-water-mark of device bytes allocated, per chip.",
        labelnames=("device",),
    )
    g_limit = reg.gauge(
        "dllama_device_bytes_limit",
        "Device memory capacity the runtime will allocate up to, per chip.",
        labelnames=("device",),
    )
    stats = device_memory_stats()
    for s in stats:
        if not s["available"]:
            continue
        g_use.labels(device=s["device"]).set(s["bytes_in_use"])
        g_peak.labels(device=s["device"]).set(s["peak_bytes_in_use"])
        g_limit.labels(device=s["device"]).set(s["bytes_limit"])
    return stats


def compare_with_analytic(
    analytic_per_chip_bytes: int,
    stats: list[dict] | None = None,
    warn_fraction: float = DIVERGENCE_WARN_FRACTION,
) -> dict:
    """Measured bytes-in-use per chip vs the analytic per-chip figure
    from ``telemetry.memory_report``. Logs a warning past
    ``warn_fraction`` (runtime holding meaningfully more than the model
    accounts for = a leak or unplanned replication; meaningfully less =
    the analytic model itself drifted). Returns a JSON-ready comparison
    (``/v1/debug/memory`` embeds it)."""
    if stats is None:
        stats = device_memory_stats()
    measured = [s for s in stats if s["available"]]
    if not measured or analytic_per_chip_bytes <= 0:
        return {
            "available": False,
            "analytic_per_chip_bytes": int(analytic_per_chip_bytes),
            "max_divergence_fraction": None,
            "per_chip": [],
        }
    per_chip = []
    worst = 0.0
    for s in measured:
        div = (
            s["bytes_in_use"] - analytic_per_chip_bytes
        ) / analytic_per_chip_bytes
        per_chip.append(
            {
                "device": s["device"],
                "bytes_in_use": s["bytes_in_use"],
                "divergence_fraction": round(div, 4),
            }
        )
        if abs(div) > abs(worst):
            worst = div
    if abs(worst) > warn_fraction:
        logger.warning(
            "device memory diverges from the analytic report by %+.1f%% "
            "(measured %d B vs analytic %d B per chip): a positive gap "
            "suggests leaked/duplicated buffers or compile scratch, a "
            "negative one a stale analytic model",
            worst * 100.0,
            max(s["bytes_in_use"] for s in measured),
            analytic_per_chip_bytes,
        )
    return {
        "available": True,
        "analytic_per_chip_bytes": int(analytic_per_chip_bytes),
        "max_divergence_fraction": round(worst, 4),
        "per_chip": per_chip,
    }
