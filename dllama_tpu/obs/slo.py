"""Windowed SLO attainment and goodput accounting.

The cumulative-since-boot counters behind ``/metrics`` answer "how has
this process done"; an admission controller or replica router needs "is
the service meeting its latency objectives RIGHT NOW". This module keeps
ring-buffered sliding windows (10s / 1m / 5m) over per-request TTFT /
TPOT / queue-wait samples and per-token completion timestamps, and folds
them into:

* **attainment** — the fraction of requests finishing inside the window
  that met their TTFT / TPOT targets (``--slo-ttft-ms`` /
  ``--slo-tpot-ms``; an unset target is vacuously met, so with no
  targets configured attainment is 1.0 and goodput equals throughput);
* **goodput** — tokens/s counted ONLY from SLO-met requests: the number
  a capacity planner actually cares about (a replica serving 1k tok/s
  at 40% attainment is not a 1k tok/s replica);
* **throughput** — tokens/s over ALL generated tokens in the window,
  from per-token timestamps (so it tracks in-flight streams, not just
  finished ones).

Surfaced three ways: ``dllama_slo_*`` gauges (refreshed at scrape /
snapshot time, one child per window), ``GET /v1/debug/slo``, and a
``slo`` section in the bench's BENCH_SERVING.json.

Thread-safety: requests finish on the scheduler thread while snapshots
run on HTTP handler threads; both sides take one short lock. Sample
rings are bounded deques — a window is additionally truncated by
capacity under extreme rates, which errs toward recency.
"""

from __future__ import annotations

import os
import threading
import time

from collections import deque
from typing import Callable

from .metrics import get_registry

WINDOWS: tuple[tuple[float, str], ...] = (
    (10.0, "10s"), (60.0, "1m"), (300.0, "5m"),
)

# the goodput gauge family name, exported so fleet-level consumers
# (fleet/obs.py scrapes it per replica and sums the 1m window) don't
# hardcode a string that must match the registration below
GOODPUT_METRIC = "dllama_slo_goodput_tokens_per_s"


def _env_float(name: str) -> float | None:
    v = os.environ.get(name, "")
    return float(v) if v else None


def resolve_slo_knobs(
    ttft_ms: float | None = None, tpot_ms: float | None = None
) -> tuple[float | None, float | None]:
    """SLO target resolution, same precedence as the lane knobs: explicit
    (CLI flag) beats env (DLLAMA_SLO_TTFT_MS / DLLAMA_SLO_TPOT_MS) beats
    the default (no target; attainment is then vacuously 1.0)."""
    if ttft_ms is None:
        ttft_ms = _env_float("DLLAMA_SLO_TTFT_MS")
    if tpot_ms is None:
        tpot_ms = _env_float("DLLAMA_SLO_TPOT_MS")
    return ttft_ms, tpot_ms


class SloTracker:
    """Sliding-window SLO/goodput accounting; see module docstring."""

    def __init__(
        self,
        ttft_target_ms: float | None = None,
        tpot_target_ms: float | None = None,
        registry: object | None = None,
        clock: Callable[[], float] = time.monotonic,
        max_requests: int = 4096,
        max_token_events: int = 16384,
    ) -> None:
        self.ttft_target_ms = ttft_target_ms
        self.tpot_target_ms = tpot_target_ms
        self._clock = clock
        self._lock = threading.Lock()
        # (t_finish, ttft_s|None, tpot_s|None, queue_wait_s|None,
        #  n_tokens, slo_met)
        self._requests: deque = deque(maxlen=max_requests)
        self._tokens: deque = deque(maxlen=max_token_events)  # (t, n)
        obs = registry if registry is not None else get_registry()
        self.g_ttft_att = obs.gauge(
            "dllama_slo_ttft_attainment",
            "Fraction of requests finishing inside the window whose TTFT "
            "met the --slo-ttft-ms target (1.0 when no target is set).",
            labelnames=("window",),
        )
        self.g_tpot_att = obs.gauge(
            "dllama_slo_tpot_attainment",
            "Fraction of requests finishing inside the window whose mean "
            "TPOT met the --slo-tpot-ms target (1.0 when no target is "
            "set).",
            labelnames=("window",),
        )
        self.g_att = obs.gauge(
            "dllama_slo_attainment",
            "Fraction of requests finishing inside the window that met "
            "ALL configured SLO targets.",
            labelnames=("window",),
        )
        # NOTE: literal name (not GOODPUT_METRIC) so the metrics-docs
        # lint sees the registration; the constant mirrors it for readers
        self.g_goodput = obs.gauge(
            "dllama_slo_goodput_tokens_per_s",
            "Completion tokens/s inside the window counting ONLY requests "
            "that met their SLO targets.",
            labelnames=("window",),
        )
        self.g_throughput = obs.gauge(
            "dllama_slo_throughput_tokens_per_s",
            "Completion tokens/s inside the window over ALL streams "
            "(per-token timestamps, so in-flight streams count).",
            labelnames=("window",),
        )
        self.g_requests = obs.gauge(
            "dllama_slo_window_requests",
            "Requests that finished inside the window.",
            labelnames=("window",),
        )

    # -- recording ---------------------------------------------------------

    def observe_request(
        self,
        ttft_s: float | None,
        tpot_s: float | None,
        queue_wait_s: float | None = None,
        n_tokens: int = 0,
        deadline_ms: float | None = None,
        total_s: float | None = None,
    ) -> bool:
        """One finished request; returns whether it met its targets. A
        missing sample (e.g. TTFT on a zero-token stream) only violates a
        target that is actually configured. A per-request ``deadline_ms``
        hint (ISSUE 20 predictive admission) is an additional target for
        THIS request only: blowing it makes the request SLO-unmet (its
        tokens drop out of goodput) even when the global targets pass."""
        met = True
        if self.ttft_target_ms is not None:
            met = ttft_s is not None and ttft_s * 1000.0 <= self.ttft_target_ms
        if met and self.tpot_target_ms is not None and tpot_s is not None:
            met = tpot_s * 1000.0 <= self.tpot_target_ms
        if met and deadline_ms is not None and total_s is not None:
            met = total_s * 1000.0 <= deadline_ms
        with self._lock:
            self._requests.append(
                (self._clock(), ttft_s, tpot_s, queue_wait_s,
                 int(n_tokens), met)
            )
        return met

    def observe_span(
        self, span: object, deadline_ms: float | None = None
    ) -> bool | None:
        """Record a finished :class:`~dllama_tpu.obs.trace.RequestSpan`.
        Only clean finishes (stop/length) count toward attainment —
        a cancelled stream says nothing about the service's latency."""
        if span.finish_reason not in ("stop", "length"):
            return None
        n = span.n_completion or 0
        tpot_s = None
        if (span.total_s is not None and span.ttft_s is not None and n > 1):
            tpot_s = (span.total_s - span.ttft_s) / (n - 1)
        return self.observe_request(
            span.ttft_s, tpot_s, span.queue_wait_s, n_tokens=n,
            deadline_ms=deadline_ms, total_s=span.total_s,
        )

    def note_tokens(self, n: int = 1) -> None:
        """Timestamp ``n`` freshly generated tokens (throughput rides on
        these, so mid-stream tokens count before the request finishes)."""
        if n <= 0:
            return
        with self._lock:
            self._tokens.append((self._clock(), n))

    # -- windows -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Per-window attainment/goodput/throughput; also refreshes every
        ``dllama_slo_*`` gauge (called at scrape time and by the debug
        endpoint)."""
        now = self._clock()
        with self._lock:
            requests = list(self._requests)
            tokens = list(self._tokens)
        windows: dict[str, dict] = {}
        for win_s, label in WINDOWS:
            cutoff = now - win_s
            reqs = [r for r in requests if r[0] >= cutoff]
            n = len(reqs)
            n_ttft_met = n_tpot_met = n_met = 0
            good_tokens = 0
            ttfts: list[float] = []
            for _, ttft_s, tpot_s, _qw, n_tok, met in reqs:
                ttft_ok = (
                    self.ttft_target_ms is None
                    or (ttft_s is not None
                        and ttft_s * 1000.0 <= self.ttft_target_ms)
                )
                tpot_ok = (
                    self.tpot_target_ms is None
                    or tpot_s is None
                    or tpot_s * 1000.0 <= self.tpot_target_ms
                )
                n_ttft_met += ttft_ok
                n_tpot_met += tpot_ok
                if met:
                    n_met += 1
                    good_tokens += n_tok
                if ttft_s is not None:
                    ttfts.append(ttft_s)
            n_window_tokens = sum(
                tn for tt, tn in tokens if tt >= cutoff
            )
            # attainment over zero requests is vacuous: report 1.0 so the
            # gauges stay finite for dashboards and the bench asserts
            ttft_att = n_ttft_met / n if n else 1.0
            tpot_att = n_tpot_met / n if n else 1.0
            att = n_met / n if n else 1.0
            goodput = good_tokens / win_s
            throughput = n_window_tokens / win_s
            ttfts.sort()
            windows[label] = {
                "window_s": win_s,
                "n_requests": n,
                "n_met": n_met,
                "ttft_attainment": round(ttft_att, 4),
                "tpot_attainment": round(tpot_att, 4),
                "attainment": round(att, 4),
                "goodput_tokens_per_s": round(goodput, 3),
                "throughput_tokens_per_s": round(throughput, 3),
                "ttft_p50_ms": (
                    round(ttfts[len(ttfts) // 2] * 1000.0, 3)
                    if ttfts else None
                ),
            }
            self.g_ttft_att.labels(window=label).set(ttft_att)
            self.g_tpot_att.labels(window=label).set(tpot_att)
            self.g_att.labels(window=label).set(att)
            self.g_goodput.labels(window=label).set(goodput)
            self.g_throughput.labels(window=label).set(throughput)
            self.g_requests.labels(window=label).set(n)
        return {
            "targets": {
                "ttft_ms": self.ttft_target_ms,
                "tpot_ms": self.tpot_target_ms,
            },
            "windows": windows,
        }
