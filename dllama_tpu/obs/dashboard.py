"""Zero-dependency live engine dashboard (``GET /dashboard``).

One self-contained HTML page — inline CSS, inline JS, canvas sparklines,
no npm, no CDN, no external URL of any kind (the ``dashboard-static``
dlint rule enforces that this module stays that way). It polls the same
JSON endpoints everything else uses:

* ``/v1/debug/series?name=&window=`` for per-series points (the
  in-process time-series store, obs/timeseries.py);
* ``/v1/health`` for the status badge (composed watchdog + anomaly
  degraded reasons).

The default panel set covers the signals an operator watches first
(lanes, queue, goodput, TTFT/TPOT, decode stall, KV free pages); a text
box adds any other series the store tracks. Rendering is deliberately
dumb — a fetch loop and ~40 lines of canvas — because the page must
work from ``curl -o dash.html`` on an air-gapped host.

The SAME page serves as the fleet dashboard on the router (ISSUE 19):
when the series index advertises ``dllama_fleet_goodput_tokens_per_s``,
the fleet default panels (aggregate goodput, TPOT skew, per-replica
TPOT, affinity hit rate, failovers) are appended, and every panel
overlays the ``replica``-labelled variants of its base series as
separate colored lines — one sparkline per replica, the skew visible at
a glance.
"""

from __future__ import annotations

DASHBOARD_CONTENT_TYPE = "text/html; charset=utf-8"

# NOTE: keep this template free of external references — no scheme
# (``//``), no ``<script src``, no ``<link href``, no ``@import``. The
# dashboard-static dlint rule scans this module's source.
DASHBOARD_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>dllama-tpu — live engine</title>
<style>
  body { background:#111418; color:#d8dee4; margin:0;
         font:13px/1.4 ui-monospace, monospace; }
  header { display:flex; align-items:center; gap:1em;
           padding:10px 16px; border-bottom:1px solid #2a2f36; }
  h1 { font-size:15px; margin:0; font-weight:600; }
  #status { padding:2px 10px; border-radius:10px; font-weight:600; }
  #status.ok { background:#1d3b24; color:#7ce38b; }
  #status.degraded { background:#4a1d1d; color:#ff8f8f; }
  #reasons { color:#ff8f8f; }
  #grid { display:grid; grid-template-columns:repeat(auto-fill,minmax(320px,1fr));
          gap:10px; padding:12px 16px; }
  .panel { background:#171b21; border:1px solid #2a2f36; border-radius:6px;
           padding:8px 10px; }
  .panel .name { color:#8b949e; overflow:hidden; text-overflow:ellipsis;
                 white-space:nowrap; }
  .panel .val { font-size:16px; font-weight:600; }
  canvas { width:100%; height:46px; display:block; margin-top:4px; }
  select, input { background:#171b21; color:#d8dee4;
                  border:1px solid #2a2f36; border-radius:4px; padding:2px 6px; }
  footer { color:#8b949e; padding:0 16px 12px; }
</style>
</head>
<body>
<header>
  <h1>dllama-tpu</h1>
  <span id="status" class="ok">…</span>
  <span id="reasons"></span>
  <label>window
    <select id="window">
      <option value="60">1m</option>
      <option value="300" selected>5m</option>
      <option value="600">10m</option>
      <option value="3600">1h</option>
    </select>
  </label>
  <input id="add" list="names" placeholder="add series…" size="34">
  <datalist id="names"></datalist>
</header>
<div id="grid"></div>
<footer>polling /v1/debug/series every 2s — single-file dashboard, no
external assets</footer>
<script>
"use strict";
const DEFAULTS = [
  "dllama_lanes_active",
  "dllama_queue_depth",
  'dllama_slo_goodput_tokens_per_s{window="1m"}',
  'dllama_slo_throughput_tokens_per_s{window="1m"}',
  "dllama_ttft_seconds_p50",
  "dllama_tpot_seconds_p50",
  "dllama_decode_stall_seconds_p99",
  "dllama_kv_pages_free",
  "dllama_spec_acceptance_rate",
  'dllama_admission_predict_error_ms_p50{signal="ttft"}',
];
const FLEET_DEFAULTS = [
  "dllama_fleet_goodput_tokens_per_s",
  "dllama_fleet_tpot_skew_ms",
  "dllama_fleet_replica_tpot_p50_ms",
  "dllama_fleet_affinity_hit_rate",
  "dllama_router_failovers_total",
];
const PALETTE = ["#58a6ff", "#7ce38b", "#ffa657", "#d2a8ff",
                 "#ff8f8f", "#79c0ff"];
let series = DEFAULTS.slice();
let fleetAdded = false;
let indexNames = [];
const grid = document.getElementById("grid");
const panels = {};

function panelFor(name) {
  if (panels[name]) return panels[name];
  const div = document.createElement("div");
  div.className = "panel";
  div.innerHTML = '<div class="name"></div><div class="val">—</div><canvas></canvas>';
  div.querySelector(".name").textContent = name;
  grid.appendChild(div);
  panels[name] = div;
  return div;
}

function spark(canvas, lines) {
  // lines: array of point arrays, one colored polyline each (line 0 is
  // the base series; 1.. are per-replica overlays). Shared y-scale so
  // replica skew reads directly off the vertical spread.
  const dpr = window.devicePixelRatio || 1;
  const w = canvas.clientWidth * dpr, h = canvas.clientHeight * dpr;
  canvas.width = w; canvas.height = h;
  const ctx = canvas.getContext("2d");
  ctx.clearRect(0, 0, w, h);
  let lo = Infinity, hi = -Infinity, t0 = Infinity, t1 = -Infinity;
  for (const pts of lines) {
    for (const [t, v] of pts) {
      lo = Math.min(lo, v); hi = Math.max(hi, v);
      t0 = Math.min(t0, t); t1 = Math.max(t1, t);
    }
  }
  if (!isFinite(lo)) return;
  if (hi === lo) { hi = lo + 1; }
  lines.forEach((pts, li) => {
    if (pts.length < 2) return;
    ctx.strokeStyle = PALETTE[li % PALETTE.length];
    ctx.lineWidth = 1.5 * dpr; ctx.beginPath();
    pts.forEach(([t, v], i) => {
      const x = ((t - t0) / Math.max(t1 - t0, 1e-9)) * (w - 2) + 1;
      const y = h - 3 - ((v - lo) / (hi - lo)) * (h - 6);
      if (i === 0) ctx.moveTo(x, y); else ctx.lineTo(x, y);
    });
    ctx.stroke();
  });
}

function replicaVariants(name) {
  // the router's series store tracks the scraped per-replica children
  // as name{replica="r0",...}; overlay them on the base panel
  if (name.includes("{")) return [];
  const prefix = name + "{";
  return indexNames.filter(
    (n) => n.startsWith(prefix) && n.includes('replica="'));
}

async function getJSON(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}

function fmt(v) {
  if (v === null || v === undefined) return "—";
  const a = Math.abs(v);
  if (a >= 1000) return v.toFixed(0);
  if (a >= 1) return v.toFixed(2);
  return v.toFixed(4);
}

async function tick() {
  const win = document.getElementById("window").value;
  try {
    const health = await getJSON("/v1/health");
    const badge = document.getElementById("status");
    badge.textContent = health.status;
    badge.className = health.status === "ok" ? "ok" : "degraded";
    document.getElementById("reasons").textContent =
      (health.degraded_reasons || []).join("  ·  ");
  } catch (e) { /* server restarting; keep polling */ }
  try {
    const idx = await getJSON("/v1/debug/series");
    indexNames = idx.names || [];
    const dl = document.getElementById("names");
    dl.innerHTML = "";
    for (const n of indexNames) {
      const o = document.createElement("option");
      o.value = n; dl.appendChild(o);
    }
    if (!fleetAdded &&
        indexNames.includes("dllama_fleet_goodput_tokens_per_s")) {
      // we are pointed at a fleet router: append the fleet panels once
      fleetAdded = true;
      for (const n of FLEET_DEFAULTS) {
        if (!series.includes(n)) series.push(n);
      }
    }
  } catch (e) { /* ignore */ }
  for (const name of series) {
    const div = panelFor(name);
    const lines = [];
    let last = null;
    for (const n of [name].concat(replicaVariants(name))) {
      try {
        const s = await getJSON(
          "/v1/debug/series?name=" + encodeURIComponent(n) +
          "&window=" + win);
        const pts = s.points || [];
        lines.push(pts);
        if (last === null && pts.length) last = pts[pts.length - 1][1];
      } catch (e) { /* series missing; panel shows a dash */ }
    }
    div.querySelector(".val").textContent = fmt(last);
    spark(div.querySelector("canvas"), lines);
  }
}

document.getElementById("add").addEventListener("change", (ev) => {
  const name = ev.target.value.trim();
  if (name && !series.includes(name)) { series.push(name); panelFor(name); }
  ev.target.value = "";
});

tick();
setInterval(tick, 2000);
</script>
</body>
</html>
"""


def render_dashboard() -> bytes:
    """The dashboard page as UTF-8 bytes (what ``GET /dashboard``
    writes)."""
    return DASHBOARD_HTML.encode("utf-8")
