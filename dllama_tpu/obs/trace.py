"""Per-request lifecycle tracing: spans over submit -> queue-wait ->
admit/lane -> prefill -> first-token -> per-token decode -> finish/cancel.

A :class:`Tracer` holds finished-request records in a bounded ring buffer
(old records fall off; a long-lived server never grows) and optionally
appends each record as one JSON line to a sink file (``--trace-out``).
A :class:`RequestSpan` is the mutable in-flight view: the serving layers
mark lifecycle points on it and the span computes the derived intervals
(queue wait, prefill span, TTFT) from a monotonic clock.

Spans are written from two threads (HTTP handler + lane scheduler) but
every field is marked by exactly one side at one lifecycle point, and
``finish`` is idempotent — the first caller wins, later calls no-op.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque


def _dumps_safe(rec: dict) -> str:
    """Serialize one trace record, tolerating non-JSON attr values by
    falling back to ``repr`` — a caller attaching an exception object or
    a numpy scalar to a span must degrade the trace line, never raise
    mid-request on the serving thread."""
    try:
        return json.dumps(rec, default=repr)
    except (TypeError, ValueError):
        # non-string keys or self-referencing structures: keep the line
        return json.dumps({"_unserializable": repr(rec)})


class RequestSpan:
    """One request's lifecycle; see module docstring. All ``*_s`` fields
    are seconds on the monotonic clock, ``submitted_unix`` is wall time."""

    def __init__(self, tracer: "Tracer | None", request_id: str | None = None,
                 path: str = "lanes", trace_id: str | None = None) -> None:
        self.tracer = tracer
        self.request_id = request_id or f"req-{uuid.uuid4().hex[:12]}"
        # fleet-level identity (ISSUE 19): the router mints one trace id
        # per client request and forwards it on every relay INCLUDING
        # failover re-issues, so the same trace id lands in every replica
        # that touched the request. None outside a fleet.
        self.trace_id = trace_id
        self.path = path
        self.submitted_unix = time.time()
        self.t_submit = time.perf_counter()
        self.lane: int | None = None
        self.queue_wait_s: float | None = None
        self.prefill_s: float | None = None
        self.ttft_s: float | None = None
        self.reused_prefix_tokens = 0
        self.n_prompt_tokens: int | None = None
        self.n_completion: int | None = None
        self.finish_reason: str | None = None
        self.total_s: float | None = None
        self._finished = False

    # -- lifecycle marks -------------------------------------------------

    def mark_admitted(self, lane: int | None = None,
                      reused_prefix_tokens: int = 0) -> float:
        """Request left the queue (lane assigned / lock acquired); returns
        the queue wait in seconds."""
        self.queue_wait_s = time.perf_counter() - self.t_submit
        self.lane = lane
        self.reused_prefix_tokens = reused_prefix_tokens
        return self.queue_wait_s

    def set_reused_prefix(self, n_tokens: int) -> None:
        self.reused_prefix_tokens = n_tokens

    def set_prefill_seconds(self, seconds: float) -> None:
        self.prefill_s = seconds

    def set_tokens(self, n_prompt: int | None = None,
                   n_completion: int | None = None) -> None:
        if n_prompt is not None:
            self.n_prompt_tokens = n_prompt
        if n_completion is not None:
            self.n_completion = n_completion

    def mark_first_token(self) -> float | None:
        """First generated token reached the host; returns TTFT seconds,
        or None when already marked (callers observe the return into the
        TTFT histogram, so the None contract keeps that single-shot)."""
        if self.ttft_s is not None:
            return None
        self.ttft_s = time.perf_counter() - self.t_submit
        return self.ttft_s

    def finish(self, reason: str, n_prompt: int | None = None,
               n_completion: int | None = None) -> dict | None:
        """Close the span and record it; idempotent (first reason wins)."""
        if self._finished:
            return None
        self._finished = True
        self.set_tokens(n_prompt, n_completion)
        self.finish_reason = reason
        self.total_s = time.perf_counter() - self.t_submit
        rec = self.to_record()
        if self.tracer is not None:
            self.tracer.record(rec)
        return rec

    # -- views -----------------------------------------------------------

    @property
    def ttft_ms(self) -> float | None:
        return None if self.ttft_s is None else self.ttft_s * 1000.0

    @property
    def queue_wait_ms(self) -> float | None:
        return None if self.queue_wait_s is None else self.queue_wait_s * 1000.0

    def to_record(self) -> dict:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "path": self.path,
            "submitted_unix": round(self.submitted_unix, 6),
            "lane": self.lane,
            "queue_wait_s": self.queue_wait_s,
            "prefill_s": self.prefill_s,
            "ttft_s": self.ttft_s,
            "reused_prefix_tokens": self.reused_prefix_tokens,
            "n_prompt_tokens": self.n_prompt_tokens,
            "n_completion": self.n_completion,
            "finish_reason": self.finish_reason,
            "cancelled": self.finish_reason == "cancelled",
            "total_s": self.total_s,
        }


class _NullSpan(RequestSpan):
    """Inert span for uninstrumented call sites: every mark is a no-op and
    nothing is ever recorded."""

    def __init__(self):
        super().__init__(tracer=None, request_id="null", path="null")
        self._finished = True  # finish() no-ops forever

    def mark_admitted(self, lane: int | None = None,
                      reused_prefix_tokens: int = 0) -> float:
        return 0.0

    def mark_first_token(self):
        return None


NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded ring buffer of finished-request records + optional JSONL
    sink; thread-safe. See module docstring."""

    def __init__(self, capacity: int = 512, sink_path: str | None = None) -> None:
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.sink_path = sink_path
        self._sink = None
        if sink_path:
            # line-buffered append: each record is durable at the newline,
            # so a crashed server still leaves complete JSONL lines behind
            self._sink = open(sink_path, "a", buffering=1)

    def span(self, request_id: str | None = None,
             path: str = "lanes", trace_id: str | None = None) -> RequestSpan:
        return RequestSpan(self, request_id, path, trace_id=trace_id)

    def record(self, rec: dict) -> None:
        line = _dumps_safe(rec)
        sink_error = None
        with self._lock:
            self._ring.append(rec)
            if self._sink is not None:
                try:
                    self._sink.write(line + "\n")
                except (ValueError, OSError) as e:
                    # closed/broken sink: keep the ring alive, but make
                    # the observability failure itself observable
                    self._sink = None
                    sink_error = e
        if sink_error is not None:
            from .recorder import get_recorder

            get_recorder().record(
                "obs_sink_error", what="trace_jsonl",
                path=self.sink_path, error=str(sink_error),
                error_type=type(sink_error).__name__,
            )

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def export(self, path: str) -> int:
        """Dump the current ring as JSONL; returns the record count.
        Non-serializable attr values degrade to ``repr`` per record."""
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(_dumps_safe(rec) + "\n")
        return len(recs)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


def read_jsonl(path: str) -> list[dict]:
    """Load a ``--trace-out`` file back into records (the round-trip
    counterpart of the sink; tests and analysis notebooks use this)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
