"""Engine watchdog: turn silent hangs into diagnosable artifacts.

A hung ``kv_adopt`` copy or a wedged scheduler loop today stops the
world with no alarm: lanes stay "active", clients block on their SSE
queues, and nothing in ``/metrics`` moves. The watchdog is a daemon
thread that audits liveness signals the scheduler feeds it:

* **beat** — one call per scheduler-loop tick, carrying how many lanes
  are active / admitting;
* **dispatch begin/end** — brackets around every engine call the
  scheduler makes (decode block, admission chunk, adopt), so the oldest
  in-flight dispatch's age is known;
* **decode / admission progress** — timestamps of the last decode-block
  dispatch and the last admission chunk/adopt that completed.

Every ``interval_s`` it evaluates three stall rules (all against an
injectable clock, so tests drive them deterministically):

1. ``dispatch-hung`` — a dispatch has been in flight longer than
   ``dispatch_timeout_s``;
2. ``scheduler-stalled`` — lanes are active or admitting but the loop
   has not beaten for ``dispatch_timeout_s`` (a deadlock outside any
   dispatch);
3. ``decode-stalled`` — lanes are active but no decode block was
   dispatched for more than ``stall_factor`` × the p99 block time
   (from ``dllama_engine_step_seconds{kind="decode_lanes"}`` via
   ``_Histogram.percentile``), floored at ``min_stall_s``;
4. ``admission-stalled`` — a request is mid-admission but no chunk or
   adopt completed for ``dispatch_timeout_s``.

On the first detection of an episode it increments
``dllama_watchdog_stalls_total{reason=}``, flips the
``dllama_watchdog_degraded`` gauge (``/v1/health`` reports
``status: degraded`` with the reason), records a ``watchdog_stall``
flight-recorder event, and writes the existing postmortem ring dump
(reason ``watchdog``) — the black box for a hang instead of a crash.
When the signals recover it clears the degraded state and records
``watchdog_recovered``; a later episode triggers a fresh postmortem.

Knobs ride the environment (no CLI surface yet):
``DLLAMA_WATCHDOG_INTERVAL_S``, ``DLLAMA_WATCHDOG_DISPATCH_TIMEOUT_S``,
``DLLAMA_WATCHDOG_STALL_FACTOR``, ``DLLAMA_WATCHDOG_MIN_STALL_S``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from ..analysis.lockwatch import make_lock
from .metrics import get_registry
from .recorder import get_recorder


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def resolve_watchdog_knobs() -> dict:
    return {
        "interval_s": _env_float("DLLAMA_WATCHDOG_INTERVAL_S", 1.0),
        "dispatch_timeout_s": _env_float(
            "DLLAMA_WATCHDOG_DISPATCH_TIMEOUT_S", 30.0
        ),
        "stall_factor": _env_float("DLLAMA_WATCHDOG_STALL_FACTOR", 20.0),
        "min_stall_s": _env_float("DLLAMA_WATCHDOG_MIN_STALL_S", 5.0),
    }


class EngineWatchdog:
    """Scheduler-liveness monitor; see module docstring."""

    def __init__(
        self,
        interval_s: float = 1.0,
        dispatch_timeout_s: float = 30.0,
        stall_factor: float = 20.0,
        min_stall_s: float = 5.0,
        block_p99: Callable[[], float | None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        registry: object | None = None,
        recorder: object | None = None,
    ) -> None:
        self.interval_s = interval_s
        self.dispatch_timeout_s = dispatch_timeout_s
        self.stall_factor = stall_factor
        self.min_stall_s = min_stall_s
        # callable returning the p99 decode-block seconds (None = no data)
        self.block_p99 = block_p99 or (lambda: None)
        self._clock = clock
        self.recorder = recorder if recorder is not None else get_recorder()
        obs = registry if registry is not None else get_registry()
        self.m_stalls = obs.counter(
            "dllama_watchdog_stalls_total",
            "Stall episodes the watchdog detected, by rule "
            "(dispatch-hung / scheduler-stalled / decode-stalled / "
            "admission-stalled). Each episode also wrote a postmortem.",
            labelnames=("reason",),
        )
        self.g_degraded = obs.gauge(
            "dllama_watchdog_degraded",
            "1 while the watchdog considers the engine stalled "
            "(/v1/health reports status=degraded), else 0.",
        )
        self.g_beat_age = obs.gauge(
            "dllama_watchdog_heartbeat_age_seconds",
            "Seconds since the scheduler loop last beat the watchdog "
            "(refreshed on every watchdog check).",
        )
        self._lock = make_lock("obs.watchdog")
        # liveness signals (mutated by the scheduler thread)
        self._last_beat: float | None = None
        self._n_active = 0
        self._n_admitting = 0
        self._dispatch_t0: float | None = None
        self._dispatch_kind: str | None = None
        self._last_decode: float | None = None
        self._last_admission: float | None = None
        self._admitting_since: float | None = None
        # detection state
        self.stalled_reason: str | None = None
        self.stalled_detail: str | None = None
        self._stalled_since: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- scheduler-side hooks (cheap: one clock read + a few stores) -------

    def beat(self, n_active: int = 0, n_admitting: int = 0) -> None:
        now = self._clock()
        with self._lock:
            self._last_beat = now
            self._n_active = n_active
            self._n_admitting = n_admitting
            if n_admitting > 0:
                if self._admitting_since is None:
                    self._admitting_since = now
            else:
                self._admitting_since = None
            if n_active > 0 and self._last_decode is None:
                # lanes just went active: arm the decode-gap rule from now,
                # not from a decode that never happened
                self._last_decode = now
            elif n_active == 0:
                self._last_decode = None

    def dispatch_begin(self, kind: str) -> None:
        now = self._clock()
        with self._lock:
            self._dispatch_t0 = now
            self._dispatch_kind = kind
            if kind == "decode_lanes":
                self._last_decode = now

    def dispatch_end(self) -> None:
        with self._lock:
            if self._dispatch_kind in ("prefill_lane_chunk", "kv_adopt"):
                self._last_admission = self._clock()
            self._dispatch_t0 = None
            self._dispatch_kind = None

    # -- detection ---------------------------------------------------------

    def _evaluate(self, now: float) -> tuple[str, str] | None:
        """(reason, detail) if any stall rule fires; caller holds no lock
        (reads are snapshotted under it here)."""
        with self._lock:
            last_beat = self._last_beat
            n_active = self._n_active
            n_admitting = self._n_admitting
            dispatch_t0 = self._dispatch_t0
            dispatch_kind = self._dispatch_kind
            last_decode = self._last_decode
            last_admission = self._last_admission
            admitting_since = self._admitting_since
        if last_beat is None:
            return None  # scheduler never ran; nothing to audit
        self.g_beat_age.set(max(now - last_beat, 0.0))
        busy = n_active > 0 or n_admitting > 0
        if dispatch_t0 is not None:
            age = now - dispatch_t0
            if age > self.dispatch_timeout_s:
                return (
                    "dispatch-hung",
                    f"{dispatch_kind} in flight for {age:.1f}s "
                    f"(timeout {self.dispatch_timeout_s:.1f}s)",
                )
        if busy and now - last_beat > self.dispatch_timeout_s:
            return (
                "scheduler-stalled",
                f"no scheduler tick for {now - last_beat:.1f}s with "
                f"{n_active} active / {n_admitting} admitting lanes",
            )
        if n_active > 0 and last_decode is not None:
            p99 = self.block_p99()
            threshold = max(
                self.min_stall_s,
                self.stall_factor * p99 if p99 else 0.0,
            )
            gap = now - last_decode
            if gap > threshold:
                return (
                    "decode-stalled",
                    f"no decode-block dispatch for {gap:.1f}s with "
                    f"{n_active} active lanes "
                    f"(threshold {threshold:.1f}s)",
                )
        if n_admitting > 0 and admitting_since is not None:
            ref = max(
                admitting_since,
                last_admission if last_admission is not None else 0.0,
            )
            gap = now - ref
            if gap > self.dispatch_timeout_s:
                return (
                    "admission-stalled",
                    f"{n_admitting} admitting lanes made no chunk/adopt "
                    f"progress for {gap:.1f}s",
                )
        return None

    def check_once(self, now: float | None = None) -> str | None:
        """One audit pass; returns the stall reason when degraded. Edge-
        triggered: only the healthy -> stalled transition pays the
        postmortem + counter, re-checks while stalled just refresh."""
        if now is None:
            now = self._clock()
        hit = self._evaluate(now)
        if hit is None:
            with self._lock:
                reason, self.stalled_reason = self.stalled_reason, None
                self.stalled_detail = None
                self._stalled_since = None
            if reason is not None:
                self.g_degraded.set(0)
                self.recorder.record("watchdog_recovered", reason=reason)
            return None
        reason, detail = hit
        with self._lock:
            first = self.stalled_reason is None
            if first:
                self.stalled_reason = reason
                self.stalled_detail = detail
                self._stalled_since = now
        if first:
            self.m_stalls.labels(reason=reason).inc()
            self.g_degraded.set(1)
            self.recorder.record(
                "watchdog_stall", reason=reason, detail=detail
            )
            # the black box for a hang instead of a crash: dump the ring
            # (dispatches that led here) while the process is still alive
            self.recorder.postmortem("watchdog", f"{reason}: {detail}")
        return reason

    # -- status / thread ---------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self.stalled_reason is not None

    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "degraded": self.stalled_reason is not None,
                "reason": self.stalled_reason,
                "detail": self.stalled_detail,
                "stalled_since_s": (
                    None if self._stalled_since is None
                    else round(self._clock() - self._stalled_since, 3)
                ),
                "in_flight_dispatch": self._dispatch_kind,
                "n_active": self._n_active,
                "n_admitting": self._n_admitting,
            }

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="dllama-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:  # the auditor must never take down serving
                import logging

                logging.getLogger(__name__).exception("watchdog check failed")
