"""Serving observability: metrics registry + request lifecycle tracing.

Zero-dependency (stdlib-only) quantitative evidence for the serving path —
the counters/gauges/histograms behind ``GET /metrics`` (Prometheus text
format) and the per-request JSONL traces behind ``--trace-out``. The
ROADMAP's north star is serving heavy traffic "as fast as the hardware
allows"; this package is how that claim gets numbers instead of vibes
(TTFT, per-token latency, queue wait, lane occupancy, prefix-cache hits).

All hooks are no-ops when the registry is disabled (``DLLAMA_OBS=0`` or
``get_registry().disable()``); an enabled histogram observation is an O(1)
bucket increment under a short lock.

PR 4 adds the ENGINE-level substrate below the request metrics: the
flight recorder (``recorder.py``, a bounded ring of structured engine
events with postmortem dumps), device memory telemetry (``device.py``,
``device.memory_stats()`` vs the analytic ``memory_report``), and
compiled-step cost analysis (``cost.py``, XLA flops/bytes vs the HBM
roofline) — all surfaced by the API server's ``/v1/debug/*`` endpoints.

PR 7 adds the third rung: span timeline tracing (``spans.py``, Chrome-
trace exports + per-request millisecond accounting behind
``/v1/debug/timeline`` and ``--timeline-out``), sliding-window SLO
attainment / goodput (``slo.py``, ``dllama_slo_*`` gauges +
``/v1/debug/slo``), and the engine watchdog (``watchdog.py``, stall
detection with auto-postmortem and a degraded ``/v1/health``).

PR 9 makes the registry continuously *watchable* in-process: a sampler
thread snapshots every counter/gauge/histogram-quantile into a bounded
two-tier time-series store (``timeseries.py``, ``/v1/debug/series``),
rolling-baseline EWMA anomaly rules over those series feed
``/v1/health``'s degraded status (``anomaly.py``), and a zero-dependency
single-file live dashboard renders the lot (``dashboard.py``,
``GET /dashboard``).
"""

from .cost import (
    extract_cost,
    hbm_peak_bytes_per_s,
    print_roofline_report,
    roofline_fraction,
    roofline_report,
    weight_bytes_per_token,
)
from .device import (
    compare_with_analytic,
    device_memory_stats,
    sample_device_memory,
)
from .anomaly import (
    AnomalyMonitor,
    AnomalyRule,
    EwmaBaseline,
    build_default_rules,
)
from .dashboard import DASHBOARD_HTML, render_dashboard
from .metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    DEFAULT_TOKEN_BUCKETS_S,
    MetricsRegistry,
    get_registry,
)
from .recorder import FlightRecorder, get_recorder
from .slo import SloTracker, resolve_slo_knobs
from .spans import SpanTracker, get_span_tracker
from .timeseries import MetricsSampler, SeriesStore, resolve_series_knobs
from .trace import NULL_SPAN, RequestSpan, Tracer
from .watchdog import EngineWatchdog, resolve_watchdog_knobs

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_S",
    "DEFAULT_TOKEN_BUCKETS_S",
    "MetricsRegistry",
    "get_registry",
    "FlightRecorder",
    "get_recorder",
    "device_memory_stats",
    "sample_device_memory",
    "compare_with_analytic",
    "extract_cost",
    "hbm_peak_bytes_per_s",
    "roofline_fraction",
    "roofline_report",
    "print_roofline_report",
    "weight_bytes_per_token",
    "NULL_SPAN",
    "RequestSpan",
    "Tracer",
    "SpanTracker",
    "get_span_tracker",
    "SloTracker",
    "resolve_slo_knobs",
    "EngineWatchdog",
    "resolve_watchdog_knobs",
    "SeriesStore",
    "MetricsSampler",
    "resolve_series_knobs",
    "AnomalyMonitor",
    "AnomalyRule",
    "EwmaBaseline",
    "build_default_rules",
    "DASHBOARD_HTML",
    "render_dashboard",
]
