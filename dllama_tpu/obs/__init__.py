"""Serving observability: metrics registry + request lifecycle tracing.

Zero-dependency (stdlib-only) quantitative evidence for the serving path —
the counters/gauges/histograms behind ``GET /metrics`` (Prometheus text
format) and the per-request JSONL traces behind ``--trace-out``. The
ROADMAP's north star is serving heavy traffic "as fast as the hardware
allows"; this package is how that claim gets numbers instead of vibes
(TTFT, per-token latency, queue wait, lane occupancy, prefix-cache hits).

All hooks are no-ops when the registry is disabled (``DLLAMA_OBS=0`` or
``get_registry().disable()``); an enabled histogram observation is an O(1)
bucket increment under a short lock.
"""

from .metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    DEFAULT_TOKEN_BUCKETS_S,
    MetricsRegistry,
    get_registry,
)
from .trace import NULL_SPAN, RequestSpan, Tracer

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_S",
    "DEFAULT_TOKEN_BUCKETS_S",
    "MetricsRegistry",
    "get_registry",
    "NULL_SPAN",
    "RequestSpan",
    "Tracer",
]
