"""Engine flight recorder: a bounded ring of structured engine events.

PR 2's request traces explain the SERVING layer (who waited, who got a
lane); when decode stalls or a compile storms the prefetch thread the
question is what the ENGINE did — the last N dispatches, their windows
and wall times, which programs compiled on which origin, when the KV
cache epoch moved. The recorder answers that: every engine-level event is
one small dict appended to a lock-guarded ring (old events fall off, a
long-lived server never grows), timestamped on the monotonic clock so
intervals survive wall-clock jumps.

Event kinds recorded today (see runtime/engine.py + runtime/api_server.py):

  * ``step_dispatch`` / ``step_complete`` — one compiled-program call
    (kind, attention window, block width / prefill bucket, position; the
    complete event carries ``ms``);
  * ``compile_start`` / ``compile_end`` — program builds with their
    origin (``dispatch`` / ``prefetch``) and compile seconds; lazily
    jitted programs record a single deferred ``compile`` event;
  * ``cache_epoch`` — KV-cache rebuilds (init / reset / crash recovery);
  * ``admit`` / ``evict`` / ``finish`` — lane-scheduler decisions;
  * ``error`` / ``scheduler_error`` — failed dispatches and scheduler-
    loop exceptions;
  * ``watchdog_stall`` / ``watchdog_recovered`` — stall episodes the
    engine watchdog (obs/watchdog.py) detected and cleared;
  * ``obs_overflow`` / ``obs_sink_error`` — observability failing at its
    own job: the span ring dropping completed spans, or a trace/timeline
    sink write failing (the layer degrades, and says so here).

**Postmortem dump**: when a ``postmortem_dir`` is configured
(``--postmortem-dir`` or ``DLLAMA_POSTMORTEM_DIR``), a crashed step or
scheduler loop writes the whole ring plus the failure reason as one JSON
file before the error propagates — the black box you read after the
crash, not the log you hoped you had enabled.

Recording is a dict build + deque append under a short lock; with the
recorder disabled (``DLLAMA_OBS=0`` disables it together with the
metrics registry) every ``record`` call returns after one attribute read.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Bounded, thread-safe ring of engine events; see module docstring."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        enabled: bool = True,
        postmortem_dir: str | None = None,
    ) -> None:
        self.capacity = capacity
        self.enabled = enabled
        self.postmortem_dir = postmortem_dir
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0  # total events ever recorded (ring drops the oldest)
        self._n_postmortems = 0
        # named callables whose outputs embed into every postmortem dump
        # (the API server registers a /v1/health snapshot and the last
        # 60 s of the anomaly-signal series): a ring dump then carries
        # the server-level evidence, diagnosable without a live server
        self._context_providers: dict[str, object] = {}

    def add_context_provider(self, key: str, fn) -> None:
        """Register (or replace) a zero-arg callable whose return value
        is embedded under ``context[key]`` in postmortem dumps. Keyed so
        test churn rebuilding server state replaces, never stacks."""
        self._context_providers[key] = fn

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, kind: str, **fields) -> None:
        """Append one event. ``t`` is monotonic seconds; ``seq`` is the
        lifetime event index (gaps at the ring head reveal how much
        history fell off)."""
        if not self.enabled:
            return
        ev = {"t": time.monotonic(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self) -> dict:
        """JSON-ready snapshot: ring contents + bookkeeping (what
        ``/v1/debug/recorder`` serves and the postmortem writes)."""
        with self._lock:
            evs = list(self._ring)
            total = self._seq
        return {
            "captured_unix": time.time(),
            "captured_monotonic": time.monotonic(),
            "capacity": self.capacity,
            "n_events": len(evs),
            "total_recorded": total,
            "dropped": max(total - len(evs), 0),
            "events": evs,
        }

    def dump_json(self) -> str:
        return json.dumps(self.dump())

    def postmortem(self, reason: str, error: BaseException | str | None = None
                   ) -> str | None:
        """Write the ring + failure context as a JSON file into
        ``postmortem_dir``; returns the path, or None when no dir is
        configured. Never raises — a broken postmortem path must not mask
        the original failure."""
        self.record(
            "postmortem", reason=reason,
            error=None if error is None else str(error),
        )
        d = self.postmortem_dir
        if not d:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._n_postmortems += 1
                n = self._n_postmortems
            path = os.path.join(
                d, f"postmortem-{int(time.time() * 1000)}-{os.getpid()}-{n}.json"
            )
            payload = self.dump()
            payload["reason"] = reason
            payload["error"] = None if error is None else str(error)
            payload["error_type"] = (
                type(error).__name__
                if isinstance(error, BaseException)
                else None
            )
            # providers run OUTSIDE the ring lock (dump/record take it)
            # and individually fail-safe: bad context must never mask
            # the original failure or the rest of the dump
            context = {}
            for key, fn in list(self._context_providers.items()):
                try:
                    context[key] = fn()
                except Exception as e:
                    context[key] = {"context_error": str(e)}
            if context:
                payload["context"] = context
            with open(path, "w") as f:
                json.dump(payload, f)
            logger.error("postmortem written to %s (reason: %s)", path, reason)
            return path
        except Exception:
            logger.exception("failed to write postmortem for %r", reason)
            return None


_DEFAULT = FlightRecorder(
    capacity=int(os.environ.get("DLLAMA_RECORDER_CAPACITY",
                                str(DEFAULT_CAPACITY))),
    enabled=os.environ.get("DLLAMA_OBS", "1") != "0",
    postmortem_dir=os.environ.get("DLLAMA_POSTMORTEM_DIR") or None,
)


def get_recorder() -> FlightRecorder:
    """The process-wide default recorder (what the engine, the lane
    scheduler and ``/v1/debug/recorder`` share)."""
    return _DEFAULT
