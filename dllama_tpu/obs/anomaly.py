"""Rolling-baseline anomaly detection over the in-process time-series.

Sarathi-SERVE and Orca both motivate continuous stall/goodput signals as
*scheduler inputs*, not just operator dashboards: a replica that is
quietly degrading (decode stalls creeping up, KV free pages draining,
goodput sagging) should say so before a hard watchdog timeout fires.
This module is that early-warning layer:

* :class:`EwmaBaseline` — exponentially weighted mean + variance of a
  signal; cheap, O(1), no sample storage.
* :class:`AnomalyRule` — one signal: a ``value_fn`` sampled every
  sampler tick, a direction (``high`` = spikes are bad, ``low`` = drops
  are bad), a z-score threshold against the EWMA baseline, absolute /
  relative deviation guards (so a near-constant baseline's tiny variance
  cannot turn noise into alarms), a warmup sample count, and a recovery
  hysteresis (``recover_ticks`` consecutive calm ticks to clear).
* :class:`AnomalyMonitor` — evaluates every rule once per sampler tick
  (wired as a ``MetricsSampler.on_sample`` callback). Edge-triggered
  like the watchdog: the calm -> anomalous transition increments
  ``dllama_anomaly_total{signal=}``, sets ``dllama_anomaly_degraded``,
  and records an ``anomaly`` flight-recorder event; recovery records
  ``anomaly_recovered``. While a rule is firing its baseline is FROZEN —
  an anomaly must not teach the baseline that anomalous is normal.

:func:`build_default_rules` wires the four production signals — decode
stall per dispatch, TTFT and TPOT per-request rates, KV free-page slope,
and 1-minute goodput — against a :class:`~.timeseries.SeriesStore`.
``/v1/health`` reports ``status: degraded`` while EITHER the watchdog or
this monitor is degraded, listing both sources' reasons.
"""

from __future__ import annotations

import math
import time
from typing import Callable

from ..analysis.lockwatch import make_lock
from .metrics import MetricsRegistry, get_registry
from .recorder import FlightRecorder, get_recorder
from .timeseries import SeriesStore


class EwmaBaseline:
    """EWMA mean/variance of a scalar signal (West-style update)."""

    __slots__ = ("alpha", "mean", "var", "n")

    def __init__(self, alpha: float = 0.05) -> None:
        self.alpha = float(alpha)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, value: float) -> None:
        if self.n == 0:
            self.mean = value
            self.var = 0.0
        else:
            delta = value - self.mean
            incr = self.alpha * delta
            self.mean += incr
            self.var = (1.0 - self.alpha) * (self.var + delta * incr)
        self.n += 1

    @property
    def std(self) -> float:
        return math.sqrt(self.var) if self.var > 0.0 else 0.0


class AnomalyRule:
    """One monitored signal; see module docstring for the semantics."""

    def __init__(
        self,
        signal: str,
        value_fn: Callable[[], float | None],
        direction: str = "high",
        z_threshold: float = 4.0,
        min_samples: int = 30,
        min_abs: float = 0.0,
        rel_frac: float = 0.0,
        min_mean: float | None = None,
        std_floor: float = 1e-6,
        recover_ticks: int = 5,
        alpha: float = 0.05,
    ) -> None:
        if direction not in ("high", "low"):
            raise ValueError(f"direction {direction!r} not in ('high','low')")
        self.signal = signal
        self.value_fn = value_fn
        self.direction = direction
        self.z_threshold = float(z_threshold)
        self.min_samples = int(min_samples)
        self.min_abs = float(min_abs)
        self.rel_frac = float(rel_frac)
        # None = no baseline-level guard: slope-style "low" signals have
        # legitimately zero/negative baseline means (steady drain)
        self.min_mean = None if min_mean is None else float(min_mean)
        self.std_floor = float(std_floor)
        self.recover_ticks = int(recover_ticks)
        self.alpha = float(alpha)

    def abnormal(self, baseline: EwmaBaseline, value: float) -> float | None:
        """The signal's z-score when ``value`` trips this rule against
        ``baseline``, else None. Guards: warmup, absolute and relative
        deviation floors, and (for ``low``) a minimum baseline level so
        an idle signal sitting at zero can never "drop"."""
        if baseline.n < self.min_samples:
            return None
        mean = baseline.mean
        std = max(baseline.std, self.std_floor)
        dev = value - mean if self.direction == "high" else mean - value
        if (
            self.direction == "low"
            and self.min_mean is not None
            and mean < self.min_mean
        ):
            return None
        if dev < self.min_abs or dev < self.rel_frac * abs(mean):
            return None
        z = dev / std
        return z if z >= self.z_threshold else None


class _RuleState:
    __slots__ = ("baseline", "active", "calm", "since", "detail")

    def __init__(self, alpha: float) -> None:
        self.baseline = EwmaBaseline(alpha)
        self.active = False
        self.calm = 0
        self.since: float | None = None
        self.detail: dict[str, object] | None = None


class AnomalyMonitor:
    """Edge-triggered rolling-baseline anomaly detection over a rule
    set; evaluated once per sampler tick (see module docstring)."""

    def __init__(
        self,
        rules: list[AnomalyRule],
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rules = list(rules)
        self._clock = clock
        self.recorder = recorder if recorder is not None else get_recorder()
        obs = registry if registry is not None else get_registry()
        self.m_anomalies = obs.counter(
            "dllama_anomaly_total",
            "Anomaly episodes by signal (decode_stall / ttft / tpot / "
            "kv_free_slope / goodput): the signal left its rolling EWMA "
            "baseline past the rule's z-score threshold.",
            labelnames=("signal",),
        )
        self.g_degraded = obs.gauge(
            "dllama_anomaly_degraded",
            "1 while any anomaly rule is firing (/v1/health reports "
            "status=degraded with the active signals), else 0.",
        )
        self._lock = make_lock("obs.anomaly")
        self._state: dict[str, _RuleState] = {
            r.signal: _RuleState(r.alpha) for r in self.rules
        }

    # -- evaluation (sampler tick) ----------------------------------------

    def evaluate(self, now: float | None = None) -> list[str]:
        """One pass over every rule; returns the signals that FIRED on
        this tick (edge, not level)."""
        if now is None:
            now = self._clock()
        fired: list[str] = []
        recovered: list[str] = []
        for rule in self.rules:
            try:
                value = rule.value_fn()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "anomaly value_fn for %r failed", rule.signal
                )
                continue
            with self._lock:
                st = self._state[rule.signal]
                if st.active:
                    # a missing sample (no traffic this tick) is a calm
                    # tick: the abnormal signal is gone
                    z = (
                        rule.abnormal(st.baseline, value)
                        if value is not None
                        else None
                    )
                    if z is not None:
                        st.calm = 0
                    else:
                        st.calm += 1
                        if st.calm >= rule.recover_ticks:
                            st.active = False
                            st.since = None
                            st.detail = None
                            st.calm = 0
                            recovered.append(rule.signal)
                    continue
                if value is None:
                    continue
                z = rule.abnormal(st.baseline, value)
                if z is not None:
                    st.active = True
                    st.calm = 0
                    st.since = now
                    st.detail = {
                        "signal": rule.signal,
                        "value": round(value, 6),
                        "baseline_mean": round(st.baseline.mean, 6),
                        "z": round(z, 2),
                    }
                    fired.append(rule.signal)
                else:
                    # calm ticks teach the baseline; anomalous (and
                    # frozen-while-active) ones never do
                    st.baseline.update(value)
        for signal in fired:
            self.m_anomalies.labels(signal=signal).inc()
            with self._lock:
                detail = self._state[signal].detail
            self.recorder.record("anomaly", **(detail or {"signal": signal}))
        for signal in recovered:
            self.recorder.record("anomaly_recovered", signal=signal)
        if fired or recovered:
            self.g_degraded.set(1.0 if self.degraded else 0.0)
        return fired

    # -- status ------------------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return any(st.active for st in self._state.values())

    def active_signals(self) -> list[str]:
        with self._lock:
            return sorted(
                s for s, st in self._state.items() if st.active
            )

    def status(self) -> dict[str, object]:
        now = self._clock()
        with self._lock:
            active = {}
            for signal, st in self._state.items():
                if not st.active:
                    continue
                detail = dict(st.detail or {})
                if st.since is not None:
                    detail["active_s"] = round(now - st.since, 3)
                active[signal] = detail
            return {
                "enabled": True,
                "degraded": bool(active),
                "active": active,
                "n_rules": len(self.rules),
                "baselines": {
                    s: {
                        "n": st.baseline.n,
                        "mean": round(st.baseline.mean, 6),
                        "std": round(st.baseline.std, 6),
                    }
                    for s, st in self._state.items()
                },
            }


# -- default production rule set ------------------------------------------


def _per_event_rate(
    store: SeriesStore, sum_name: str, count_name: str
) -> Callable[[], float | None]:
    """Per-tick mean of a histogram signal: delta(sum)/delta(count) since
    the previous tick, None on ticks with no new observations (the rule
    then neither fires nor learns)."""
    prev: dict[str, float | None] = {"sum": None, "count": None}

    def fn() -> float | None:
        s = store.latest(sum_name)
        c = store.latest(count_name)
        if s is None or c is None:
            return None
        ps, pc = prev["sum"], prev["count"]
        prev["sum"], prev["count"] = s, c
        if ps is None or pc is None or c <= pc:
            return None
        return (s - ps) / (c - pc)

    return fn


def _slope(store: SeriesStore, name: str) -> Callable[[], float | None]:
    """Per-tick delta of a gauge (its discrete slope)."""
    prev: dict[str, float | None] = {"v": None}

    def fn() -> float | None:
        v = store.latest(name)
        if v is None:
            return None
        pv = prev["v"]
        prev["v"] = v
        if pv is None:
            return None
        return v - pv

    return fn


def _level(store: SeriesStore, name: str) -> Callable[[], float | None]:
    def fn() -> float | None:
        return store.latest(name)

    return fn


# public value-fn builders: external rule sets (fleet/obs.py builds its
# fleet-level rules over the router's series store) compose the same
# primitives the default rules use
level = _level
slope = _slope
per_event_rate = _per_event_rate


# the series behind the default rules, in one place: postmortem dumps
# embed the trailing window of exactly these signals (obs/recorder.py
# context providers), so a ring dump carries the same evidence the live
# anomaly monitor would have been looking at
DEFAULT_SIGNAL_SERIES = (
    "dllama_decode_stall_seconds_sum",
    "dllama_decode_stall_seconds_count",
    "dllama_ttft_seconds_sum",
    "dllama_ttft_seconds_count",
    "dllama_tpot_seconds_sum",
    "dllama_tpot_seconds_count",
    "dllama_kv_pages_free",
    'dllama_slo_goodput_tokens_per_s{window="1m"}',
    'dllama_admission_predict_error_ms_sum{signal="ttft"}',
    'dllama_admission_predict_error_ms_count{signal="ttft"}',
)


def build_default_rules(store: SeriesStore) -> list[AnomalyRule]:
    """The production signal set, reading the series the sampler just
    recorded (the monitor runs as an ``on_sample`` callback, after the
    tick's values land in the store):

    * ``decode_stall`` — mean inter-dispatch stall per decode block this
      tick spiking over its baseline (an admission storm or host hiccup
      a streaming client feels);
    * ``ttft`` / ``tpot`` — per-request first-token and per-token
      latency rates creeping up;
    * ``kv_free_slope`` — the paged-KV free list draining persistently
      faster than its baseline churn (a retain leak or runaway fanout
      exhausts the pool long before allocation actually fails);
    * ``goodput`` — the 1-minute SLO-met tokens/s dropping far below its
      baseline while the engine is supposed to be under load;
    * ``predict_error`` — the predictive admission controller's mean
      TTFT forecast error blowing up over its baseline: the EWMA
      self-calibration (runtime/admission.py) should keep this bounded,
      so a sustained spike means the predictor is steering admission /
      EDF ordering / preemption with a broken model of the machine.
    """
    return [
        AnomalyRule(
            "decode_stall",
            _per_event_rate(
                store,
                "dllama_decode_stall_seconds_sum",
                "dllama_decode_stall_seconds_count",
            ),
            direction="high",
            z_threshold=4.0,
            min_abs=0.05,
            rel_frac=1.0,
            min_samples=30,
        ),
        AnomalyRule(
            "ttft",
            _per_event_rate(
                store, "dllama_ttft_seconds_sum", "dllama_ttft_seconds_count"
            ),
            direction="high",
            z_threshold=4.0,
            min_abs=0.25,
            rel_frac=1.0,
            min_samples=30,
        ),
        AnomalyRule(
            "tpot",
            _per_event_rate(
                store, "dllama_tpot_seconds_sum", "dllama_tpot_seconds_count"
            ),
            direction="high",
            z_threshold=4.0,
            min_abs=0.02,
            rel_frac=1.0,
            min_samples=30,
        ),
        AnomalyRule(
            "kv_free_slope",
            _slope(store, "dllama_kv_pages_free"),
            direction="low",
            z_threshold=4.0,
            min_abs=1.0,
            min_samples=30,
        ),
        AnomalyRule(
            "goodput",
            _level(store, 'dllama_slo_goodput_tokens_per_s{window="1m"}'),
            direction="low",
            z_threshold=4.0,
            min_mean=1.0,
            rel_frac=0.5,
            min_samples=60,
        ),
        AnomalyRule(
            "predict_error",
            _per_event_rate(
                store,
                'dllama_admission_predict_error_ms_sum{signal="ttft"}',
                'dllama_admission_predict_error_ms_count{signal="ttft"}',
            ),
            direction="high",
            z_threshold=4.0,
            min_abs=50.0,
            rel_frac=2.0,
            min_samples=30,
        ),
    ]
