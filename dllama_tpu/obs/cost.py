"""Compiled-step cost analysis + HBM roofline accounting.

Decode on this hardware is HBM-bandwidth-bound (docs/silicon_r03.md, the
q40i4 format PR): a decode step's floor is (bytes it must read) / (HBM
peak). XLA already knows the first number for every compiled program —
``compiled.cost_analysis()`` reports flops and bytes accessed — so this
module harvests it from the engine's compile cache, pairs it with the
measured step-time histograms, and turns "is decode as fast as the
hardware allows?" into a single achieved-vs-roofline fraction instead of
a guess.

The same analytic weight-read model the bench uses
(``weight_bytes_per_token``) lives here so the CLI can print a startup
roofline report next to the memory/ICI reports: bytes per decoded token
per chip, the HBM floor in ms/token, and the implied tok/s ceiling.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax

if TYPE_CHECKING:
    from ..formats.model_file import LlmHeader

# Approximate per-chip HBM peak bandwidth by TPU generation, bytes/s
# (public chip specs; matched against jax.devices()[0].device_kind,
# lowercase substring). Unknown kinds — and the CPU test backend — report
# None, and every roofline figure downstream degrades to "unavailable"
# rather than a made-up fraction.
HBM_PEAK_BYTES_PER_S = {
    "v6e": 1640e9,
    "v6": 1640e9,
    "v5p": 2765e9,
    "v5e": 819e9,
    "v5litepod": 819e9,
    "v4": 1228e9,
    "v3": 900e9,
}


def hbm_peak_bytes_per_s() -> float | None:
    """Per-chip HBM peak for the current backend, or None when unknown
    (CPU, unrecognized accelerator)."""
    if jax.default_backend() != "tpu":
        return None
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for marker, peak in HBM_PEAK_BYTES_PER_S.items():
        if marker in kind:
            return peak
    return None


def extract_cost(compiled: object) -> dict | None:
    """{flops, bytes_accessed} from an executable's ``cost_analysis()``,
    or None when the object is not an AOT-compiled executable (lazily
    jitted step fns), the backend returns nothing, or the surface raises.
    jax has returned both a bare dict and a one-per-module list across
    versions; both shapes are accepted."""
    fn = getattr(compiled, "cost_analysis", None)
    if fn is None:
        return None
    try:
        ca = fn()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed")
    if flops is None and bytes_accessed is None:
        return None
    return {
        "flops": float(flops or 0.0),
        "bytes_accessed": float(bytes_accessed or 0.0),
    }


def roofline_fraction(
    bytes_accessed: float, step_seconds: float, peak_bytes_per_s: float | None
) -> float | None:
    """Fraction of the HBM roofline a measured step achieved: achieved
    bytes/s over peak. None when any input is missing/degenerate."""
    if (
        peak_bytes_per_s is None
        or peak_bytes_per_s <= 0
        or step_seconds <= 0
        or bytes_accessed <= 0
    ):
        return None
    return (bytes_accessed / step_seconds) / peak_bytes_per_s


def analytic_step_seconds(
    bytes_accessed: float | None, peak_bytes_per_s: float | None
) -> float | None:
    """Bandwidth-bound lower bound on one dispatch's wall time: the
    program's cost-analysis bytes pushed through the chip's HBM peak.
    The LoadPredictor's cold-start floor (runtime/admission.py) before
    any measured step percentiles exist; None when the cost or the peak
    is unknown (CPU backend, lazily jitted program)."""
    if (
        bytes_accessed is None
        or bytes_accessed <= 0
        or peak_bytes_per_s is None
        or peak_bytes_per_s <= 0
    ):
        return None
    return float(bytes_accessed) / float(peak_bytes_per_s)


def weight_bytes_per_token(
    h: "LlmHeader", weight_format: str, i8_group: int = 512
) -> int:
    """HBM bytes of weights a single decode step must read: every matmul
    weight once (MoE: attention weights + the active experts' share).
    Q40 device layout = int8 values + f32 scale per 32 block = 1.125
    B/weight; grouped int8 = 1 + 4/G; packed nibbles + f16 scales =
    0.5625; dense bf16 = 2 B/weight. (Shared by bench.py and the startup
    roofline report.)"""
    bpw = {
        "q40": 1.125,
        "q40i8": 1.0 + 4.0 / i8_group,
        "q40i4": 0.5 + 2.0 / 32.0,
    }.get(weight_format, 2.0)
    att = h.dim * h.q_dim + 2 * h.dim * h.kv_dim + h.q_dim * h.dim
    ffn = 3 * h.dim * h.ff_dim
    if h.n_experts:
        ffn *= h.n_active_experts  # ragged kernel reads active experts only
    total = (h.n_layers * (att + ffn) + h.dim * h.vocab_size) * bpw
    if h.n_experts:
        total += h.n_layers * h.dim * h.n_experts * 4  # f32 gate
    return int(total)


def program_cost_ceilings(
    family: str,
    *,
    steps: int = 1,
    tokens: int = 1,
    param_bytes: float = 0.0,
    cache_bytes: float = 0.0,
    pool_bytes: float = 0.0,
    param_elems: float = 0.0,
    cache_elems: float = 0.0,
    slack: float = 8.0,
    paged: bool = False,
) -> dict:
    """Per-program {bytes_accessed, flops} ceilings for the xlalint cost
    budget gate, derived from the same roofline model as
    ``weight_bytes_per_token``: a forward step fundamentally reads the
    weights once plus the touched KV window (bytes floor) and does
    ~2 flops per weight per token plus the attention reads (flops
    floor). The ``slack`` multiple (default 8x) makes these CLIFF
    guards, not tight bounds — a program only trips one when it does
    work a whole multiple of its analytic floor (the classic regather /
    accidental-replication failure mode), so backend fusion differences
    never flap the gate. Copy programs (``kv_adopt``/``kv_publish``/
    ``kv_page_copy``) move pages between KV buffers: their bytes
    ceiling is a slack multiple of the buffers involved and their flops
    are ~0 (a flat allowance covers index arithmetic). ``paged=True``
    marks a pool-native lane program (PR 16): its forward reads K/V
    through a page-table gather out of the pool and scatters the new
    rows back, so its ceiling grows by ~two extra pool traversals per
    step — page indirection that costs MORE than that is exactly the
    regression this gate exists to catch. Draft-model programs
    (``draft_prefill``/``draft_step``) are plain forwards over the DRAFT
    checkpoint's params/cache: callers pass the draft spec trees and the
    same forward math applies (``draft_step`` autoregresses, so its
    ``steps`` is the draft length k).
    """
    if family in ("kv_adopt", "kv_publish", "kv_page_copy"):
        return {
            "bytes_accessed": slack * (cache_bytes + pool_bytes),
            "flops": slack * cache_elems + 1e6,
        }
    steps = max(1, steps)
    tokens = max(1, tokens)
    # the cache term scales with the token count: a t-wide prefill's
    # attention reads/writes the KV window per token, and on small
    # models that activation traffic dwarfs the one-time weight read
    base_bytes = param_bytes + (1.0 + tokens) * cache_bytes + pool_bytes
    if paged:
        # page-table gather (view materialization) + row scatter-back
        base_bytes += 2.0 * pool_bytes
    return {
        "bytes_accessed": slack * steps * base_bytes,
        "flops": (
            slack * steps * (2.0 * param_elems * tokens
                             + 4.0 * cache_elems * tokens)
            + 1e6
        ),
    }


def roofline_report(
    h: "LlmHeader", weight_format: str, tp: int = 1, pp: int = 1,
    i8_group: int = 512, spec_k: int = 0
) -> dict:
    """Analytic decode roofline for this model/format/layout: weight-read
    bytes per token per chip (weights shard over tp x pp; dp/sp replicate
    them, each replica reading its own copy) and, when the backend's HBM
    peak is known, the ms/token floor + tok/s ceiling. With speculation
    on (``spec_k`` > 0) one verify dispatch — one weight pass — emits up
    to ``spec_k + 1`` tokens, so the weight-bound ceiling scales by the
    achieved tokens-per-weight-pass, which live decoding reports as the
    ``dllama_spec_tokens_per_weight_pass`` gauge (floor 1.0 = nothing
    accepted, ceiling ``spec_k + 1`` = every draft accepted)."""
    shards = max(tp, 1) * max(pp, 1)
    per_chip = weight_bytes_per_token(h, weight_format, i8_group) // shards
    peak = hbm_peak_bytes_per_s()
    rep: dict = {
        "weight_bytes_per_token_per_chip": per_chip,
        "hbm_peak_bytes_per_s": peak,
        "min_ms_per_token": None,
        "max_tok_s_per_chip": None,
        "spec_tokens_per_pass_floor": None,
        "spec_tokens_per_pass_ceiling": None,
    }
    if peak:
        rep["min_ms_per_token"] = per_chip / peak * 1000.0
        rep["max_tok_s_per_chip"] = peak / per_chip if per_chip else None
    if spec_k > 0:
        rep["spec_tokens_per_pass_floor"] = 1.0
        rep["spec_tokens_per_pass_ceiling"] = float(spec_k + 1)
    return rep


def print_roofline_report(
    h: "LlmHeader", weight_format: str, tp: int = 1, pp: int = 1,
    i8_group: int = 512, spec_k: int = 0
) -> dict:
    """Startup roofline printout (rides next to the memory/ICI reports in
    cli.load_engine); returns the report dict it printed."""
    rep = roofline_report(
        h, weight_format, tp=tp, pp=pp, i8_group=i8_group, spec_k=spec_k
    )
    gb = rep["weight_bytes_per_token_per_chip"] / 1e9
    if rep["hbm_peak_bytes_per_s"]:
        print(
            f"📐 Roofline: {gb:.3f} GB weight reads/token/chip @ "
            f"{rep['hbm_peak_bytes_per_s'] / 1e9:.0f} GB/s HBM peak -> "
            f">= {rep['min_ms_per_token']:.2f} ms/token "
            f"(<= {rep['max_tok_s_per_chip']:.1f} tok/s/chip)"
        )
    else:
        print(
            f"📐 Roofline: {gb:.3f} GB weight reads/token/chip "
            f"(HBM peak unknown on the {jax.default_backend()!r} backend; "
            "no tok/s ceiling)"
        )
    if rep["spec_tokens_per_pass_ceiling"] is not None:
        print(
            f"📐 Speculation: 1 weight pass emits 1.0..."
            f"{rep['spec_tokens_per_pass_ceiling']:.1f} tokens (k="
            f"{spec_k}; live: dllama_spec_tokens_per_weight_pass)"
        )
    return rep
