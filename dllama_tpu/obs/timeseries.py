"""In-process metrics time-series: bounded ring history for every metric.

Everything the registry exports today is a point-in-time snapshot: a
``/metrics`` scrape tells you where the counters stand NOW, and nothing
retains what they looked like ten seconds ago. The ROADMAP's
replica-router and SLO-aware-scheduling items both need the *time
dimension* — "is goodput dropping", "is the KV free list draining" —
and so does a human watching a live engine. This module keeps it, in
process, with zero dependencies:

* :class:`SeriesStore` — one bounded two-tier ring per series. Tier 1
  holds full-resolution samples (~1 s, ``interval_s``) for the recent
  past (``tier1_retention_s``, default 10 min); tier 2 holds a
  downsampled point per ``DOWNSAMPLE_EVERY`` tier-1 samples (~10 s) out
  to ``retention_s`` (default 1 h). Counter-kind series downsample by
  LAST value (the cumulative count at the bucket edge stays exact);
  gauge-kind series downsample by MEAN (a 10 s bucket of a noisy gauge
  keeps its level, not a lucky instant).
* :class:`MetricsSampler` — a named, joinable daemon thread
  (``dllama-series-sampler``) that every ``interval_s`` runs the
  registry's refresh hooks (so on-demand gauges — SLO windows, device
  memory, step cost — are current *independent of Prometheus scrapes*),
  snapshots ``registry.flat_values()`` into the store, and invokes any
  ``on_sample`` callbacks (the anomaly monitor rides here). The clock is
  injectable; ``sample_once()`` is the thread body's unit-testable core.

Surfaced by ``GET /v1/debug/series?name=&window=`` and the live
``GET /dashboard`` sparklines (obs/dashboard.py). Knobs:
``--series-retention`` / ``DLLAMA_SERIES_RETENTION_S``,
``DLLAMA_SERIES_INTERVAL_S``, ``DLLAMA_SERIES_MAX``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable

from ..analysis.lockwatch import make_lock
from .metrics import MetricsRegistry, get_registry
from .recorder import FlightRecorder, get_recorder

# tier-2 keeps one point per this many tier-1 samples (~10 s at the
# default 1 s interval)
DOWNSAMPLE_EVERY = 10


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


def resolve_series_knobs(
    retention_s: float | None = None, interval_s: float | None = None
) -> tuple[float, float]:
    """Time-series knob resolution, same precedence as the lane knobs:
    explicit (CLI ``--series-retention``) beats env
    (DLLAMA_SERIES_RETENTION_S / DLLAMA_SERIES_INTERVAL_S) beats the
    defaults (1 h retention, 1 s sampling)."""
    if retention_s is None:
        retention_s = _env_float("DLLAMA_SERIES_RETENTION_S", 3600.0)
    if interval_s is None:
        interval_s = _env_float("DLLAMA_SERIES_INTERVAL_S", 1.0)
    return float(retention_s), float(interval_s)


class _Series:
    """One metric's two-tier ring; appends are O(1), bounds are deques."""

    __slots__ = (
        "kind", "tier1", "tier2", "_bucket_n", "_bucket_sum", "_bucket_last"
    )

    def __init__(self, kind: str, tier1_cap: int, tier2_cap: int) -> None:
        self.kind = kind
        self.tier1: deque[tuple[float, float]] = deque(maxlen=tier1_cap)
        self.tier2: deque[tuple[float, float]] = deque(maxlen=tier2_cap)
        self._bucket_n = 0
        self._bucket_sum = 0.0
        self._bucket_last = 0.0

    def append(self, t: float, value: float) -> None:
        self.tier1.append((t, value))
        self._bucket_n += 1
        self._bucket_sum += value
        self._bucket_last = value
        if self._bucket_n >= DOWNSAMPLE_EVERY:
            down = (
                self._bucket_last
                if self.kind == "counter"
                else self._bucket_sum / self._bucket_n
            )
            self.tier2.append((t, down))
            self._bucket_n = 0
            self._bucket_sum = 0.0


class SeriesStore:
    """Bounded ring time-series over registry samples; see module doc.

    Thread-safety: the sampler thread appends while HTTP handler threads
    query; one short lock guards the series map and the rings. The store
    is bounded three ways — tier-1/tier-2 deque capacities and a cap on
    the number of distinct series (``max_series``): past the cap, new
    names are dropped and counted in ``dllama_series_dropped_total``
    (recorded once as an ``obs_overflow`` event, not once per sample).
    """

    def __init__(
        self,
        interval_s: float = 1.0,
        retention_s: float = 3600.0,
        tier1_retention_s: float = 600.0,
        max_series: int = 2048,
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
    ) -> None:
        self.interval_s = max(float(interval_s), 0.001)
        self.retention_s = max(float(retention_s), self.interval_s)
        self.tier1_retention_s = min(
            max(float(tier1_retention_s), self.interval_s), self.retention_s
        )
        self.max_series = int(max_series)
        self._tier1_cap = max(
            int(round(self.tier1_retention_s / self.interval_s)), 1
        )
        self._tier2_cap = max(
            int(round(
                self.retention_s / (self.interval_s * DOWNSAMPLE_EVERY)
            )),
            1,
        )
        self._lock = make_lock("obs.series")
        self._series: dict[str, _Series] = {}
        self._overflowed = False
        self.recorder = recorder if recorder is not None else get_recorder()
        obs = registry if registry is not None else get_registry()
        self.m_samples = obs.counter(
            "dllama_series_samples_total",
            "Sampler ticks folded into the in-process time-series store.",
        )
        self.g_tracked = obs.gauge(
            "dllama_series_tracked",
            "Distinct series the time-series store currently retains.",
        )
        self.m_dropped = obs.counter(
            "dllama_series_dropped_total",
            "New series names dropped because the store hit its "
            "max-series bound (existing series keep sampling).",
        )

    # -- writes (sampler thread) ------------------------------------------

    def record(
        self, now: float, values: dict[str, tuple[str, float]]
    ) -> None:
        """Fold one sampler tick — ``flat_values()`` output — into the
        rings."""
        dropped = 0
        with self._lock:
            for name, (kind, value) in values.items():
                s = self._series.get(name)
                if s is None:
                    if len(self._series) >= self.max_series:
                        dropped += 1
                        continue
                    s = _Series(kind, self._tier1_cap, self._tier2_cap)
                    self._series[name] = s
                s.append(now, value)
            n_tracked = len(self._series)
        self.m_samples.inc()
        self.g_tracked.set(n_tracked)
        if dropped:
            self.m_dropped.inc(dropped)
            if not self._overflowed:
                self._overflowed = True
                self.recorder.record(
                    "obs_overflow", what="series_store",
                    max_series=self.max_series,
                )

    # -- reads (HTTP handler threads) -------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def query(
        self, name: str, window_s: float, now: float | None = None
    ) -> dict[str, object] | None:
        """Points for ``name`` covering the trailing ``window_s`` seconds
        before ``now`` (default: the series' newest sample, so readers
        need no clock of their own and fake-clock tests stay
        deterministic); tier 1 serves windows it fully retains, tier 2
        serves the rest. None when the series does not exist."""
        window_s = max(float(window_s), self.interval_s)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            use_tier1 = window_s <= self.tier1_retention_s
            ring = s.tier1 if use_tier1 else s.tier2
            if now is None:
                now = s.tier1[-1][0] if s.tier1 else 0.0
            cutoff = now - window_s
            points = [[t, v] for t, v in ring if t >= cutoff]
            kind = s.kind
        return {
            "name": name,
            "kind": kind,
            "tier": "1s" if use_tier1 else "10s",
            "interval_s": (
                self.interval_s if use_tier1
                else self.interval_s * DOWNSAMPLE_EVERY
            ),
            "window_s": window_s,
            "now": now,
            "points": points,
        }

    def latest(self, name: str) -> float | None:
        """Most recent tier-1 value of ``name`` (anomaly rules read
        signals through this)."""
        with self._lock:
            s = self._series.get(name)
            if s is None or not s.tier1:
                return None
            return s.tier1[-1][1]


class MetricsSampler:
    """Named, joinable sampler thread over a :class:`SeriesStore`.

    Every ``interval_s`` (injectable via the store) it runs the
    registry's refresh hooks, folds ``flat_values()`` into the store and
    calls each ``on_sample(now)`` callback. ``sample_once()`` is the
    whole tick, callable directly under a fake clock — the thread adds
    only the wait loop, and ``stop()`` joins it so engine teardown (and
    test churn) never leaks a sampler mutating the shared registry."""

    def __init__(
        self,
        store: SeriesStore,
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.store = store
        self.registry = registry if registry is not None else get_registry()
        self._clock = clock
        self.on_sample: list[Callable[[float], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample_once(self, now: float | None = None) -> float:
        """One tick: refresh hooks -> snapshot -> callbacks. Returns the
        tick timestamp."""
        if now is None:
            now = self._clock()
        self.registry.run_refresh_hooks()
        self.store.record(now, self.registry.flat_values())
        for cb in list(self.on_sample):
            try:
                cb(now)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "series on_sample callback failed"
                )
        return now

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dllama-series-sampler", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Idempotent stop-and-join (server close and test churn both
        call it; a joined sampler cannot race the next ApiState's
        registry writes)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.store.interval_s):
            try:
                self.sample_once()
            except Exception:  # the sampler must never take down serving
                import logging

                logging.getLogger(__name__).exception(
                    "series sampler tick failed"
                )
