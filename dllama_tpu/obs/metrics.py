"""Thread-safe metrics registry with Prometheus text-format rendering.

Counters, gauges, and fixed-bucket histograms — the stdlib-only subset of
a Prometheus client that the serving path needs. Design constraints:

* **Negligible overhead.** A histogram observation is one ``bisect`` into
  a fixed bucket list plus an increment, under a lock held only for that
  observation; when the registry is disabled every mutate call returns
  before taking the lock, so instrumentation hooks cost one attribute
  read on the cold path.
* **Idempotent registration.** ``registry.counter(name)`` returns the
  existing family when `name` was already registered (the API server and
  engine are built many times per test process against the shared default
  registry); re-registering under a different metric type raises.
* **Valid scrape output.** ``render()`` emits Prometheus text format
  0.0.4 (``# HELP``/``# TYPE`` per family, cumulative ``_bucket{le=}``
  rows + ``_sum``/``_count`` for histograms) so a stock Prometheus server
  can scrape ``GET /metrics`` unmodified.
* **One refresh path.** Gauges that are computed on demand (the windowed
  ``dllama_slo_*`` values, per-chip device memory, compiled-step cost)
  register a named *refresh hook* on the registry; every reader that
  wants current values — the ``/metrics`` scrape handler AND the
  time-series sampler (``timeseries.py``) — calls
  ``run_refresh_hooks()`` first. Before this existed the refresh lived
  inside the scrape handler only, so any non-scrape reader saw whatever
  the last scrape left behind (the PR 9 stale-gauge bug).
"""

from __future__ import annotations

import logging
import os
import threading
from bisect import bisect_left
from typing import Callable, Sequence

# serving latencies (TTFT, queue wait, prefill, dispatch): 1 ms .. 60 s
DEFAULT_LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# per-token decode latency (TPOT): 0.5 ms .. 1 s
DEFAULT_TOKEN_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as repr."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Counter:
    __slots__ = ("_family", "_value")

    def __init__(self, family: "_Family") -> None:
        self._family = family
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        reg = self._family.registry
        if not reg.enabled:
            return
        with reg._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class _Gauge:
    __slots__ = ("_family", "_value")

    def __init__(self, family: "_Family") -> None:
        self._family = family
        self._value = 0.0

    def set(self, value: float) -> None:
        reg = self._family.registry
        if not reg.enabled:
            return
        with reg._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        reg = self._family.registry
        if not reg.enabled:
            return
        with reg._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class _Histogram:
    __slots__ = ("_family", "_counts", "_sum", "_count")

    def __init__(self, family: "_Family") -> None:
        self._family = family
        # one slot per bucket + the +Inf overflow slot
        self._counts = [0] * (len(family.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        reg = self._family.registry
        if not reg.enabled:
            return
        idx = bisect_left(self._family.buckets, value)  # le is inclusive
        with reg._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float | None:
        """Estimate the ``q``-quantile (q in [0, 1]) by linear
        interpolation inside the bucket holding the target rank — the
        single shared implementation the watchdog and the SLO windows
        read p99 block time from. Returns None with no observations.
        Samples beyond the last finite bucket clamp to its edge (the
        +Inf bucket has no upper edge to interpolate toward)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        reg = self._family.registry
        with reg._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return None
        buckets = self._family.buckets
        target = q * total
        cum = 0.0
        for i, n in enumerate(counts[:-1]):
            prev = cum
            cum += n
            if cum >= target:
                lo = buckets[i - 1] if i > 0 else 0.0
                hi = buckets[i]
                if n == 0:
                    return hi
                return lo + (hi - lo) * (target - prev) / n
        return buckets[-1] if buckets else None


_CHILD_TYPES = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class _Family:
    """One named metric family; children are keyed by label-value tuples.
    A family declared without labelnames has a single default child and
    proxies ``inc``/``set``/``dec``/``observe`` straight to it."""

    def __init__(self, registry, name, help_, mtype, labelnames, buckets):
        self.registry = registry
        self.name = name
        self.help = help_
        self.type = mtype
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets else ()
        self._children: dict[tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = _CHILD_TYPES[mtype](self)

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self.registry._lock:
                child = self._children.setdefault(
                    key, _CHILD_TYPES[self.type](self)
                )
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()"
            )
        return self._children[()]

    # no-label conveniences
    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    def percentile(self, q: float) -> float | None:
        return self._default().percentile(q)

    def child_values(self) -> dict[tuple[str, ...], float]:
        return {k: c.value for k, c in sorted(self._children.items())
                if not isinstance(c, _Histogram)}

    def _label_str(self, key: tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self, out: list[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.type}")
        for key in sorted(self._children):
            child = self._children[key]
            if self.type == "histogram":
                cum = 0
                for le, n in zip(self.buckets, child._counts):
                    cum += n
                    le_lbl = 'le="' + _fmt(le) + '"'
                    out.append(
                        f"{self.name}_bucket"
                        f"{self._label_str(key, le_lbl)} {cum}"
                    )
                cum += child._counts[-1]
                inf_lbl = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket"
                    f"{self._label_str(key, inf_lbl)} {cum}"
                )
                out.append(
                    f"{self.name}_sum{self._label_str(key)} "
                    f"{_fmt(child._sum)}"
                )
                out.append(
                    f"{self.name}_count{self._label_str(key)} {child._count}"
                )
            else:
                out.append(
                    f"{self.name}{self._label_str(key)} {_fmt(child.value)}"
                )


# histogram quantiles the sampler snapshots per family child (series
# names: <name>_p50{...} / <name>_p99{...}, gauge-kind)
SAMPLE_QUANTILES: tuple[tuple[float, str], ...] = ((0.5, "p50"), (0.99, "p99"))


class MetricsRegistry:
    """Thread-safe registry of metric families; see module docstring."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        # name -> callable; insertion-ordered, keyed so rebuilding an
        # ApiState/engine against the shared default registry REPLACES
        # its hook instead of stacking a dead closure per rebuild
        self._refresh_hooks: dict[str, Callable[[], object]] = {}

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def _get(
        self,
        name: str,
        help_: str,
        mtype: str,
        labelnames: Sequence[str],
        buckets: Sequence[float] | None = None,
    ) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.type}, "
                        f"cannot re-register as {mtype}"
                    )
                return fam
            fam = _Family(self, name, help_, mtype, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get(name, help, "counter", labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get(name, help, "gauge", labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ) -> _Family:
        return self._get(name, help, "histogram", labelnames, buckets)

    # -- refresh hooks -----------------------------------------------------

    def add_refresh_hook(self, name: str, fn: Callable[[], object]) -> None:
        """Register (or replace) the named on-demand gauge refresher.
        Hooks run in registration order from ``run_refresh_hooks()``."""
        with self._lock:
            self._refresh_hooks[name] = fn

    def remove_refresh_hook(self, name: str) -> None:
        with self._lock:
            self._refresh_hooks.pop(name, None)

    def run_refresh_hooks(self) -> None:
        """Bring every on-demand gauge current. Called by BOTH readers —
        the ``/metrics`` scrape handler and the time-series sampler — so
        they see the same values. Hooks run outside the registry lock
        (they set gauges, which retakes it) and a failing hook logs and
        is skipped: one broken refresher must not take down the scrape
        or the sampler thread."""
        if not self.enabled:
            return
        with self._lock:
            hooks = list(self._refresh_hooks.items())
        for name, fn in hooks:
            try:
                fn()
            except Exception:
                logging.getLogger(__name__).exception(
                    "metrics refresh hook %r failed", name
                )

    def render(self) -> str:
        out: list[str] = []
        with self._lock:
            for fam in self._families.values():
                fam.render(out)
        return "\n".join(out) + "\n" if out else ""

    def flat_values(self) -> dict[str, tuple[str, float]]:
        """Every current sample as ``series name -> (kind, value)`` — the
        time-series sampler's view of the registry. Counters and gauges
        contribute one entry per labelled child
        (``name{label="v"}``); a histogram child contributes its
        cumulative ``_count``/``_sum`` (counter-kind, rate-able) plus the
        :data:`SAMPLE_QUANTILES` estimates (``_p50``/``_p99``,
        gauge-kind). Does NOT run the refresh hooks — callers that want
        current on-demand gauges call :meth:`run_refresh_hooks` first."""
        out: dict[str, tuple[str, float]] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            with self._lock:
                children = sorted(fam._children.items())
            for key, child in children:
                labels = fam._label_str(key)
                if isinstance(child, _Histogram):
                    out[f"{fam.name}_count{labels}"] = (
                        "counter", float(child.count),
                    )
                    out[f"{fam.name}_sum{labels}"] = (
                        "counter", float(child.sum),
                    )
                    for q, suffix in SAMPLE_QUANTILES:
                        v = child.percentile(q)
                        if v is not None:
                            out[f"{fam.name}_{suffix}{labels}"] = (
                                "gauge", float(v),
                            )
                else:
                    out[f"{fam.name}{labels}"] = (fam.type, float(child.value))
        return out

    def reset(self) -> None:
        """Drop all families and refresh hooks (tests/bench only — live
        scrapers rely on counters being monotonic for the process
        lifetime)."""
        with self._lock:
            self._families.clear()
            self._refresh_hooks.clear()


_DEFAULT = MetricsRegistry(enabled=os.environ.get("DLLAMA_OBS", "1") != "0")


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what `/metrics` serves)."""
    return _DEFAULT
