"""Fleet topologies: bring up N replicas + the front door.

Two modes:

* :class:`InProcessFleet` — N engines + N ``api_server`` instances + one
  router, all in this process. This is what the tests and the bench use
  on CPU: deterministic (shared seeds), cheap to tear down, and — since
  the obs registry and flight recorder are process-global — a single
  ``/metrics`` scrape on ANY port already aggregates the whole fleet.
  Each replica gets a ``replica_id`` (``r0``, ``r1``, ...) so seeded
  chaos can target exactly one of them (``sse_flush:op=r1:nth=3``).
* ``main()`` — the ops entry point: spawns each replica as its own
  ``python -m dllama_tpu.runtime.api_server`` subprocess (its own
  device footprint, its own metrics), waits for their health endpoints,
  then runs the router in the foreground. docs/fleet.md has the
  runbook.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from dataclasses import dataclass, field

from ..tokenizer import ChatTemplateType, Tokenizer
from .replicas import ReplicaRegistry
from .router import resolve_fleet_knobs, serve_router


@dataclass
class FleetHandle:
    """Everything a test/bench needs to drive and tear down a fleet."""

    router: object                      # ThreadingHTTPServer (router)
    replicas: list[tuple[str, object]]  # (name, ThreadingHTTPServer)
    registry: ReplicaRegistry
    threads: list[threading.Thread] = field(default_factory=list)

    @property
    def router_url(self) -> str:
        return f"http://127.0.0.1:{self.router.server_address[1]}"

    @property
    def replica_urls(self) -> dict[str, str]:
        return {
            name: f"http://127.0.0.1:{srv.server_address[1]}"
            for name, srv in self.replicas
        }

    @property
    def router_state(self):
        """The router's :class:`~dllama_tpu.fleet.router.RouterState` —
        tests and the bench reach the fleet observability plane here
        (``.fleet`` for scrape/sampler/monitor, ``.spans`` for router
        spans, ``.ledger`` for request history)."""
        return self.router.state

    def close(self) -> None:
        self.router.shutdown()
        self.router.server_close()  # stops the health poller too
        for _, srv in self.replicas:
            srv.shutdown()
            srv.server_close()


def launch_inprocess_fleet(
    model_path: str,
    tokenizer_path: str,
    n_replicas: int = 2,
    batch_size: int = 2,
    chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
    engine_kwargs: dict | None = None,
    serve_kwargs: dict | None = None,
    router_kwargs: dict | None = None,
) -> FleetHandle:
    """N lane-scheduler replicas of one tiny model + the router, all on
    127.0.0.1 ephemeral ports. Every replica decodes greedily with the
    same seed, which is what makes mid-stream failover byte-identity
    testable: any replica continues any sibling's stream exactly."""
    import jax.numpy as jnp

    from ..runtime.api_server import serve
    from ..runtime.engine import InferenceEngine

    if n_replicas < 1:
        raise ValueError(f"need at least one replica, got {n_replicas}")
    ekw = dict(
        tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=batch_size,
    )
    ekw.update(engine_kwargs or {})
    skw = dict(serve_kwargs or {})
    replicas: list[tuple[str, object]] = []
    threads: list[threading.Thread] = []
    for i in range(n_replicas):
        name = f"r{i}"
        # independent Tokenizer per replica: encode is stateless but the
        # tokenizer's own incremental decoder is not, and replicas must
        # not share mutable state
        tok = Tokenizer(tokenizer_path)
        engine = InferenceEngine(model_path, tokenizer=tok, **ekw)
        srv = serve(
            engine, tok, host="127.0.0.1", port=0,
            chat_template_type=chat_template_type,
            replica_id=name, **skw,
        )
        t = threading.Thread(  # dlint: disable=thread-hygiene — serve_forever returns at FleetHandle.close()'s shutdown(); the daemon thread exits with it
            target=srv.serve_forever, daemon=True,
            name=f"fleet-replica-{name}",
        )
        t.start()
        replicas.append((name, srv))
        threads.append(t)
    registry = ReplicaRegistry(
        {
            name: f"http://127.0.0.1:{srv.server_address[1]}"
            for name, srv in replicas
        },
        poll_interval_s=0.5,
    )
    rkw = dict(
        chat_template_type=chat_template_type,
        stall_timeout_s=30.0,
    )
    rkw.update(router_kwargs or {})
    router = serve_router(
        registry, Tokenizer(tokenizer_path), host="127.0.0.1", port=0,
        **rkw,
    )
    rt = threading.Thread(  # dlint: disable=thread-hygiene — serve_forever returns at FleetHandle.close()'s shutdown(); the daemon thread exits with it
        target=router.serve_forever, daemon=True, name="fleet-router"
    )
    rt.start()
    threads.append(rt)
    return FleetHandle(
        router=router, replicas=replicas, registry=registry,
        threads=threads,
    )


def _wait_health(url: str, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    last = "no attempt"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/v1/health", timeout=5.0):
                return
        except OSError as e:
            last = f"{type(e).__name__}: {e}"
            time.sleep(0.5)
    raise TimeoutError(f"replica at {url} never became healthy ({last})")


def main(argv=None) -> None:
    """Ops entry: N replica subprocesses + the router in the foreground.

    python -m dllama_tpu.fleet.launch --model m.m --tokenizer t.t \\
        --n-replicas 2 --base-port 9990 --port 9980 --batch-size 4
    """
    import argparse
    import subprocess
    import sys

    from ..tokenizer import CHAT_TEMPLATE_NAMES

    parser = argparse.ArgumentParser(
        prog="dllama-tpu-fleet",
        description="Spawn an N-replica fleet + router (docs/fleet.md)",
    )
    parser.add_argument("--model", required=True)
    parser.add_argument("--tokenizer", required=True)
    parser.add_argument("--n-replicas", type=int, default=2)
    parser.add_argument("--base-port", type=int, default=9990,
                        help="replica i listens on base-port + i")
    parser.add_argument("--port", type=int, default=9980,
                        help="router port")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--batch-size", type=int, default=4)
    parser.add_argument("--max-streams", type=int, default=None)
    parser.add_argument("--chat-template", default=None,
                        choices=sorted(CHAT_TEMPLATE_NAMES))
    parser.add_argument("--routing", default="affinity",
                        choices=("affinity", "random"))
    args = parser.parse_args(argv)

    procs: list[subprocess.Popen] = []
    replicas: dict[str, str] = {}
    try:
        for i in range(args.n_replicas):
            name, port = f"r{i}", args.base_port + i
            cmd = [
                sys.executable, "-m", "dllama_tpu.runtime.api_server",
                "--model", args.model, "--tokenizer", args.tokenizer,
                "--host", args.host, "--port", str(port),
                "--batch-size", str(args.batch_size),
                "--replica-id", name,
            ]
            if args.max_streams is not None:
                cmd += ["--max-streams", str(args.max_streams)]
            if args.chat_template:
                cmd += ["--chat-template", args.chat_template]
            procs.append(subprocess.Popen(cmd))
            replicas[name] = f"http://{args.host}:{port}"
        for url in replicas.values():
            _wait_health(url)
        _, _, _, poll_s = resolve_fleet_knobs()
        registry = ReplicaRegistry(replicas, poll_interval_s=poll_s)
        ttype = (
            CHAT_TEMPLATE_NAMES[args.chat_template]
            if args.chat_template
            else ChatTemplateType.UNKNOWN
        )
        server = serve_router(
            registry, Tokenizer(args.tokenizer),
            host=args.host, port=args.port,
            chat_template_type=ttype, routing=args.routing,
        )
        print(
            f"Fleet router: http://{args.host}:{args.port}/v1/ "
            f"({len(replicas)} replicas)\n"
            f"Fleet dashboard: http://{args.host}:{args.port}/dashboard "
            f"· metrics: /metrics · timelines: /v1/fleet/timeline"
        )
        try:
            server.serve_forever()
        finally:
            server.server_close()
    finally:
        for p in procs:
            p.terminate()  # SIGTERM = graceful drain on the replica
        for p in procs:
            try:
                p.wait(timeout=90.0)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    main()
