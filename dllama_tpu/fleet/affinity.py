"""Prefix-affinity routing: consistent hashing over the prompt's first K
token ids.

Pure functions + a small hash ring, no I/O, no clocks — everything here
is unit-testable with plain lists. The router tokenizes each request
ONCE (with the same chat template + encode flags as the replica's
admission path) and hashes the first ``k`` token ids; the ring maps that
key to a stable replica order. Repeated prompts — and prompts sharing a
long system prefix — land on the same replica, whose radix tree then
serves the prefix from cache. Hashing uses blake2b, not Python's
``hash()``, so the assignment is stable across processes and runs
(``PYTHONHASHSEED`` must not matter for routing determinism).

``plan_route`` layers health on top of the ring order: dead and
draining replicas are skipped, saturated replicas (admission-aware:
``in_flight >= max_streams`` from the health capacity block) are
skipped, and degraded replicas are deprioritized to last-resort rather
than skipped — a degraded replica still serves, it is just not the
first choice. Every diversion away from the affinity target is recorded
with a reason so the router can count spills per cause.
"""

from __future__ import annotations

import bisect
import hashlib
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable, Mapping, Sequence

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (typing only)
    from .replicas import ReplicaView

DEFAULT_AFFINITY_K = 32
# virtual nodes per replica; enough that removing one replica moves only
# ~1/N of the keyspace instead of reshuffling everything.
VNODES = 64


def _h(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    )


def prefix_affinity_key(tokens: Sequence[int], k: int = DEFAULT_AFFINITY_K) -> int:
    """Stable 64-bit key for the first ``k`` token ids of a prompt.

    Two prompts sharing their first ``k`` tokens (e.g. a common system
    prompt) hash to the same key and therefore the same replica — that
    is the whole point: the replica's radix tree already holds the
    shared prefix.
    """
    if k <= 0:
        raise ValueError(f"affinity k must be positive, got {k}")
    head = tokens[: int(k)]
    payload = b"".join(
        int(t).to_bytes(4, "big", signed=False) for t in head
    )
    return _h(b"prefix:" + payload)


class HashRing:
    """Consistent-hash ring over replica names with virtual nodes.

    ``order(key)`` walks the ring clockwise from the key's position and
    returns every distinct replica once, in ring order — the first entry
    is the affinity target, the rest are the deterministic spill /
    failover order for that key.
    """

    def __init__(self, names: Iterable[str] = (), vnodes: int = VNODES):
        if vnodes <= 0:
            raise ValueError(f"vnodes must be positive, got {vnodes}")
        self._vnodes = int(vnodes)
        self._points: list[int] = []          # sorted ring positions
        self._owner: dict[int, str] = {}      # position -> replica name
        self._names: set[str] = set()
        for name in names:
            self.add(name)

    @property
    def names(self) -> frozenset[str]:
        return frozenset(self._names)

    def add(self, name: str) -> None:
        if name in self._names:
            return
        self._names.add(name)
        for v in range(self._vnodes):
            point = _h(f"replica:{name}#{v}".encode())
            # blake2b collisions across <1k points are effectively
            # impossible; if one ever happens, first owner keeps it.
            if point in self._owner:
                continue
            self._owner[point] = name
            bisect.insort(self._points, point)

    def remove(self, name: str) -> None:
        if name not in self._names:
            return
        self._names.discard(name)
        keep = [p for p in self._points if self._owner[p] != name]
        for p in self._points:
            if self._owner[p] == name:
                del self._owner[p]
        self._points = keep

    def order(self, key: int) -> list[str]:
        """All replicas in clockwise ring order starting at ``key``."""
        if not self._points:
            return []
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_left(self._points, int(key) % (1 << 64))
        n = len(self._points)
        for i in range(n):
            name = self._owner[self._points[(start + i) % n]]
            if name not in seen:
                seen.add(name)
                out.append(name)
            if len(seen) == len(self._names):
                break
        return out


@dataclass
class RoutePlan:
    """Ordered candidates for one request plus why anyone was skipped."""

    target: str | None            # affinity target (ring-first), pre-health
    candidates: list[str] = field(default_factory=list)
    skipped: list[tuple[str, str]] = field(default_factory=list)  # (name, reason)

    @property
    def spill_reason(self) -> str | None:
        """Why the affinity target was diverted, if it was.

        None when the first candidate IS the target (an affinity hit)
        or when there is no viable candidate at all.
        """
        if self.target is None or not self.candidates:
            return None
        if self.candidates[0] == self.target:
            return None
        for name, reason in self.skipped:
            if name == self.target:
                return reason
        return "degraded"  # target demoted to last-resort, not skipped


def plan_route(
    ring_order: Sequence[str],
    views: Mapping[str, "ReplicaView"],
) -> RoutePlan:
    """Filter a ring order through replica health into a RoutePlan.

    Dead / draining / saturated replicas are skipped with a reason;
    degraded replicas are demoted behind every healthy candidate but
    kept as last resort. Deterministic: same inputs, same plan.
    """
    plan = RoutePlan(target=ring_order[0] if ring_order else None)
    degraded: list[str] = []
    for name in ring_order:
        view = views.get(name)
        if view is None or view.state == "dead":
            plan.skipped.append((name, "dead"))
            continue
        if view.state == "draining":
            plan.skipped.append((name, "draining"))
            continue
        if view.saturated:
            plan.skipped.append((name, "saturated"))
            continue
        if view.state == "degraded":
            degraded.append(name)
            continue
        plan.candidates.append(name)
    plan.candidates.extend(degraded)
    return plan
