"""Replica fleet: a prefix-affinity front door over N engine replicas.

The single-replica serving story (drain, shedding, structured retryable
errors, degraded health, byte-identical recovery and park/resume) scales
out here: a lightweight stdlib HTTP router (:mod:`.router`) fronts N
independent ``api_server`` replicas, routing each request by
prompt-prefix hash to the replica whose radix tree likely holds the
prefix (:mod:`.affinity`), spilling to siblings when the target is
degraded / draining / saturated (:mod:`.replicas`), and surviving
replica death mid-stream by resuming the stream on a sibling with the
already-emitted tokens as prompt prefix — the PR 12 recovery contract,
one level up. :mod:`.launch` brings up an N-replica CPU topology for
tests and the bench. See docs/fleet.md.
"""

from .affinity import HashRing, RoutePlan, plan_route, prefix_affinity_key
from .replicas import (
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTHY,
    Replica,
    ReplicaRegistry,
    ReplicaView,
)
from .router import RouterState, resolve_fleet_knobs, serve_router

__all__ = [
    "HashRing",
    "RoutePlan",
    "plan_route",
    "prefix_affinity_key",
    "HEALTHY",
    "DEGRADED",
    "DRAINING",
    "DEAD",
    "Replica",
    "ReplicaRegistry",
    "ReplicaView",
    "RouterState",
    "resolve_fleet_knobs",
    "serve_router",
]
