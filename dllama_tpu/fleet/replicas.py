"""Replica registry: health polling + a per-replica state machine.

Each replica runs an ordinary ``api_server`` whose ``GET /v1/health``
already reports ``ok`` / ``degraded`` (+ ``degraded_reasons``) /
``draining`` and — new with the fleet — a ``capacity`` block
(``max_streams``, ``kv_native``, ``lanes``, ``parked``, ``in_flight``)
so the router can make admission-aware spill decisions instead of
hashing blindly. The registry polls every replica, maps the payload
onto a four-state machine::

    healthy <-> degraded <-> draining        (what the replica reports)
         \\________ dead ________/            (poll failures / router veto)

A replica becomes ``dead`` after ``fail_threshold`` consecutive poll
failures (or immediately via :meth:`ReplicaRegistry.mark_dead` when the
router's connection attempt is refused) and is revived by the next
successful poll — death is an observation, not a sentence.

The poller takes an injectable ``fetch`` callable and ``clock`` so unit
tests drive the state machine synchronously with canned payloads; the
background thread is only started by :meth:`start` (the router does
this, tests usually call :meth:`poll_once` directly).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Iterable, Mapping

from ..obs.recorder import get_recorder

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
DEAD = "dead"

_STATUS_TO_STATE = {
    "ok": HEALTHY,
    "degraded": DEGRADED,
    "draining": DRAINING,
}

DEFAULT_POLL_S = 2.0
DEFAULT_FAIL_THRESHOLD = 3
_FETCH_TIMEOUT_S = 5.0


def _default_fetch(base_url: str) -> dict:
    with urllib.request.urlopen(
        f"{base_url}/v1/health", timeout=_FETCH_TIMEOUT_S
    ) as r:
        return json.loads(r.read())


@dataclass
class Replica:
    """One replica's registry entry (mutable, guarded by the registry
    lock)."""

    name: str
    base_url: str
    state: str = HEALTHY
    consecutive_failures: int = 0
    last_health: dict = field(default_factory=dict)
    last_error: str = ""
    last_change_ts: float = 0.0


@dataclass(frozen=True)
class ReplicaView:
    """Immutable per-replica snapshot handed to routing (affinity's
    ``plan_route``) — no locks needed downstream."""

    name: str
    base_url: str
    state: str
    max_streams: int = 0          # 0 = unknown capacity: never saturated
    in_flight: int = 0
    lanes: int = 0
    parked: int = 0
    kv_native: bool = False
    degraded_reasons: tuple[str, ...] = ()

    @property
    def saturated(self) -> bool:
        return self.max_streams > 0 and self.in_flight >= self.max_streams


class ReplicaRegistry:
    """Thread-safe registry over a fixed replica set.

    ``fetch(base_url) -> dict`` must return the replica's ``/v1/health``
    payload or raise; ``clock()`` stamps state transitions (monotonic by
    default, injectable for tests).
    """

    def __init__(
        self,
        replicas: Mapping[str, str] | Iterable[tuple[str, str]],
        fetch: Callable[[str], dict] | None = None,
        clock: Callable[[], float] = time.monotonic,
        poll_interval_s: float = DEFAULT_POLL_S,
        fail_threshold: int = DEFAULT_FAIL_THRESHOLD,
    ):
        pairs = (
            replicas.items() if isinstance(replicas, Mapping) else replicas
        )
        self._replicas: dict[str, Replica] = {
            name: Replica(name=name, base_url=url) for name, url in pairs
        }
        if not self._replicas:
            raise ValueError("registry needs at least one replica")
        self._fetch = fetch if fetch is not None else _default_fetch
        self._clock = clock
        self.poll_interval_s = float(poll_interval_s)
        self.fail_threshold = int(fail_threshold)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.recorder = get_recorder()

    # ------------------------------------------------------------- state

    @property
    def names(self) -> list[str]:
        return list(self._replicas)

    def url_of(self, name: str) -> str:
        return self._replicas[name].base_url

    def urls(self) -> dict[str, str]:
        """Name -> base URL for every registered replica (the fleet
        scraper and recorder/timeline fan-outs iterate this)."""
        return {name: rep.base_url for name, rep in self._replicas.items()}

    def _transition(self, rep: Replica, state: str, reason: str) -> None:
        """Caller holds the lock."""
        if rep.state == state:
            return
        prev, rep.state = rep.state, state
        rep.last_change_ts = self._clock()
        self.recorder.record(
            "replica_state",
            replica=rep.name,
            prev=prev,
            state=state,
            reason=reason,
        )

    def poll_once(self) -> dict[str, str]:
        """Poll every replica once; returns ``{name: state}``."""
        for rep in self._replicas.values():
            try:
                payload = self._fetch(rep.base_url)
            except (OSError, ValueError) as e:
                with self._lock:
                    rep.consecutive_failures += 1
                    rep.last_error = f"{type(e).__name__}: {e}"
                    if rep.consecutive_failures >= self.fail_threshold:
                        self._transition(rep, DEAD, "poll_failures")
                continue
            state = _STATUS_TO_STATE.get(str(payload.get("status")), DEGRADED)
            with self._lock:
                rep.consecutive_failures = 0
                rep.last_error = ""
                rep.last_health = payload
                self._transition(rep, state, "health")
        return {name: rep.state for name, rep in self._replicas.items()}

    def mark_dead(self, name: str, reason: str = "router") -> None:
        """Router veto: a connection to this replica was refused; stop
        routing to it until a health poll revives it."""
        rep = self._replicas.get(name)
        if rep is None:
            return
        with self._lock:
            rep.consecutive_failures = max(
                rep.consecutive_failures, self.fail_threshold
            )
            self._transition(rep, DEAD, reason)

    def mark_draining(self, name: str) -> None:
        """Immediate local echo of a forwarded ``POST /v1/drain`` — the
        next poll would notice anyway, but routing should stop now."""
        rep = self._replicas.get(name)
        if rep is None:
            return
        with self._lock:
            self._transition(rep, DRAINING, "drain_forwarded")

    def views(self) -> dict[str, ReplicaView]:
        out: dict[str, ReplicaView] = {}
        with self._lock:
            for name, rep in self._replicas.items():
                cap = rep.last_health.get("capacity") or {}
                out[name] = ReplicaView(
                    name=name,
                    base_url=rep.base_url,
                    state=rep.state,
                    max_streams=int(cap.get("max_streams", 0) or 0),
                    in_flight=int(cap.get("in_flight", 0) or 0),
                    lanes=int(cap.get("lanes", 0) or 0),
                    parked=int(cap.get("parked", 0) or 0),
                    kv_native=bool(cap.get("kv_native", False)),
                    degraded_reasons=tuple(
                        rep.last_health.get("degraded_reasons") or ()
                    ),
                )
        return out

    def snapshot(self) -> dict[str, dict]:
        """Full per-replica detail for ``GET /v1/fleet``."""
        with self._lock:
            return {
                name: {
                    "url": rep.base_url,
                    "state": rep.state,
                    "consecutive_failures": rep.consecutive_failures,
                    "last_error": rep.last_error,
                    "health": rep.last_health,
                }
                for name, rep in self._replicas.items()
            }

    # ------------------------------------------------------------ poller

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, name="fleet-health-poller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # pragma: no cover - belt and braces
                self.recorder.record(
                    "replica_poll_error", error=f"{type(e).__name__}: {e}"
                )
            self._stop.wait(self.poll_interval_s)
