"""The fleet front door: prefix-affinity routing + mid-stream failover.

A stdlib ``ThreadingHTTPServer`` (same style as ``runtime/api_server``,
deliberately engine-free — the router process never imports jax) that
fronts N replicas:

* **Affinity.** Each ``POST /v1/chat/completions`` is tokenized ONCE at
  the router with exactly the replica's admission recipe (chat template
  with ``append_generation_prompt=True``, then ``encode(is_start=True,
  add_special_tokens=True)``); the first K token ids hash onto a
  consistent ring (:mod:`.affinity`), so repeated and shared-prefix
  prompts land on the replica whose radix tree holds their prefix.
* **Spill.** The ring order is filtered through replica health
  (:mod:`.replicas`): dead/draining/saturated siblings are skipped,
  degraded ones demoted to last resort, and a 429/503 shed or refused
  connection at request time moves to the next candidate. Every
  diversion counts in ``dllama_router_spills_total{reason}``.
* **Mid-stream failover.** Replicas stream with ``include_tokens``, so
  every SSE chunk carries the exact generated token ids
  (``dllama_tokens``) and their raw decoded text (``dllama_piece``).
  When a replica dies mid-stream (EOF, stall past the watchdog read
  timeout, or an in-stream retryable error), the router first emits the
  catch-up delta — the exact text consumed but still held back by the
  dead replica's EOS detector — then re-issues the request to the next
  sibling as ``resume_tokens`` = prompt tokens + emitted tokens. The
  sibling's recovery-admission path (radix re-match + chunked
  re-prefill) continues the stream byte-identically under greedy
  decoding, on the SAME client connection. docs/fleet.md spells out the
  contract and its two edge cases (stop strings and incomplete UTF-8
  spanning the boundary).

* **Fleet observability (ISSUE 19).** The router mints a trace id +
  request id per client request and forwards them as
  ``x-dllama-trace``/``x-dllama-request`` headers on every relay
  INCLUDING failover re-issues, so every replica that touched a request
  records the same fleet-level identity. The router keeps its OWN
  :class:`~dllama_tpu.obs.spans.SpanTracker` (tokenize, route_plan,
  relay, stall_detect, failover, catch_up_synthesis spans);
  ``GET /v1/fleet/timeline?request_id=`` stitches the router fragment
  with per-replica ``/v1/debug/timeline`` fragments into one Perfetto
  trace where a mid-stream failover renders as a single continuous
  request with the gap attributed to an explicit ``failover`` span.
  ``GET /metrics`` re-exports replica metrics with a ``replica`` label
  and the fleet aggregates (:mod:`.obs`); the fleet anomaly rules feed
  ``/v1/health`` ``degraded_reasons``; ``/dashboard`` overlays
  per-replica sparklines.

Knobs resolve CLI-beats-env-beats-default via the ``DLLAMA_FLEET_*``
family: ``DLLAMA_FLEET_AFFINITY_K``, ``DLLAMA_FLEET_FAILOVER_MAX``,
``DLLAMA_FLEET_STALL_S``, ``DLLAMA_FLEET_POLL_S``; the observability
plane adds the ``DLLAMA_FLEET_OBS_*`` family (:mod:`.obs`).
"""

from __future__ import annotations

import http.client
import json
import os
import random
import threading
import time
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlencode, urlsplit

from ..obs.dashboard import DASHBOARD_CONTENT_TYPE, render_dashboard
from ..obs.metrics import get_registry
from ..obs.recorder import get_recorder
from ..obs.spans import SpanTracker
from ..tokenizer import (
    CHAT_TEMPLATE_NAMES,
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    Tokenizer,
)
from .affinity import (
    DEFAULT_AFFINITY_K,
    HashRing,
    RoutePlan,
    plan_route,
    prefix_affinity_key,
)
from .obs import (
    PID_STRIDE,
    FleetObs,
    RequestLedger,
    resolve_fleet_obs_knobs,
    stitch_timelines,
)
from .replicas import ReplicaRegistry

DEFAULT_FAILOVER_MAX = 3
DEFAULT_STALL_S = 120.0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def resolve_fleet_knobs(
    affinity_k: int | None = None,
    failover_max: int | None = None,
    stall_timeout_s: float | None = None,
    poll_interval_s: float | None = None,
) -> tuple[int, int, float, float]:
    """Router knob resolution: explicit value (CLI flag / constructor)
    beats the ``DLLAMA_FLEET_*`` env knob beats the default — the same
    ladder as the engine's lane/stream knobs."""
    k = (
        int(affinity_k)
        if affinity_k is not None
        else int(_env_float("DLLAMA_FLEET_AFFINITY_K", DEFAULT_AFFINITY_K))
    )
    fmax = (
        int(failover_max)
        if failover_max is not None
        else int(_env_float("DLLAMA_FLEET_FAILOVER_MAX", DEFAULT_FAILOVER_MAX))
    )
    stall = (
        float(stall_timeout_s)
        if stall_timeout_s is not None
        else _env_float("DLLAMA_FLEET_STALL_S", DEFAULT_STALL_S)
    )
    poll = (
        float(poll_interval_s)
        if poll_interval_s is not None
        else _env_float("DLLAMA_FLEET_POLL_S", 2.0)
    )
    if k <= 0:
        raise ValueError(f"affinity k must be positive, got {k}")
    return k, max(0, fmax), stall, poll


def _sse_write(wfile, data: str) -> None:
    """One HTTP-chunked SSE frame (mirror of the replica server's)."""
    raw = data.encode("utf-8")
    wfile.write(f"{len(raw):x}\r\n".encode() + raw + b"\r\n")


class _StreamDeath(Exception):
    """An upstream replica's SSE stream died recoverably mid-flight:
    EOF / broken chunking, a read stall past the watchdog timeout, or an
    in-stream retryable error frame. The relay fails over."""


def _retry_after_s(value, default: int = 2) -> int:
    """Parse an upstream ``Retry-After`` header value (delta-seconds
    form) into a positive int; ``default`` on absent/malformed input.
    HTTP-date form is not produced by the replicas, so it falls through
    to the default rather than being parsed."""
    if value is None:
        return default
    try:
        s = int(float(value))
    except (TypeError, ValueError):
        return default
    return s if s > 0 else default


class RouterState:
    """Shared router state: registry + ring + tokenizer + metrics."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        tokenizer: Tokenizer,
        chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
        model_name: str = "dllama-fleet",
        affinity_k: int | None = None,
        failover_max: int | None = None,
        stall_timeout_s: float | None = None,
        routing: str = "affinity",
        seed: int = 0,
        fleet_obs: FleetObs | None = None,
    ):
        if routing not in ("affinity", "random"):
            raise ValueError(f"unknown routing mode {routing!r}")
        self.registry = registry
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.routing = routing
        self.start_unix = time.time()
        k, fmax, stall, _ = resolve_fleet_knobs(
            affinity_k, failover_max, stall_timeout_s
        )
        self.affinity_k = k
        self.failover_max = fmax
        self.stall_timeout_s = stall
        # the router's prompt rendering MUST mirror the replica's
        # admission path token-for-token — the affinity key hashes the
        # very ids the replica's radix tree stores (tests cross-check
        # against the replica's reported usage.prompt_tokens)
        stops = [
            tokenizer.vocab[t].decode("utf-8", "replace")
            for t in tokenizer.eos_token_ids
        ]
        self.template = ChatTemplateGenerator(
            chat_template_type,
            tokenizer.chat_template,
            stops[0] if stops else "",
        )
        self.ring = HashRing(registry.names)
        # predicted-wait bookkeeping (ISSUE 20): a replica that sheds
        # with Retry-After is predicting its own queue-drain time, so
        # the router remembers "busy until" per replica and demotes
        # still-backing-off siblings in the spill order instead of
        # hammering them with requests they already said they'd shed
        self._shed_until: dict[str, float] = {}
        self._shed_lock = threading.Lock()
        # deterministic per-request RNG stream for routing="random" (the
        # bench's affinity-off baseline): string seeding is stable across
        # processes, unlike hash()-seeded tuples
        self._seed = seed
        self._n_requests = 0
        self._count_lock = threading.Lock()
        self.obs = get_registry()
        self.recorder = get_recorder()
        self.m_requests = self.obs.counter(
            "dllama_router_requests_total",
            "Router requests by serving replica and outcome (ok, error, "
            "shed, refused, died, client_gone, unavailable, ...).",
            labelnames=("replica", "outcome"),
        )
        self.m_failovers = self.obs.counter(
            "dllama_router_failovers_total",
            "Mid-stream failovers: a replica's SSE stream died and the "
            "router resumed it on a sibling via resume_tokens.",
        )
        self.m_affinity_hits = self.obs.counter(
            "dllama_router_affinity_hits_total",
            "Requests served by their prefix-affinity target replica "
            "(first streamed-from replica == consistent-hash target).",
        )
        self.m_spills = self.obs.counter(
            "dllama_router_spills_total",
            "Requests diverted off their affinity target by reason "
            "(dead, draining, saturated, degraded, shed, refused).",
            labelnames=("reason",),
        )
        self.m_stalls = self.obs.counter(
            "dllama_router_stalls_total",
            "Mid-stream failovers triggered specifically by a read stall "
            "past the watchdog timeout (subset of failovers_total).",
        )
        self.m_gap = self.obs.histogram(
            "dllama_router_failover_gap_seconds",
            "Client-visible failover gap: replica stream death to the "
            "catch-up delta landing from the sibling (the recovery "
            "latency the fleet bench watches at p99).",
        )
        # the router's OWN span tracker — deliberately NOT the process
        # global: in the in-process fleet the global tracker belongs to
        # the replicas, and the stitcher must be able to fetch router
        # and replica fragments as disjoint span sets
        self.spans = SpanTracker()
        _, _, ledger_cap = resolve_fleet_obs_knobs()
        self.ledger = RequestLedger(ledger_cap)
        # scrape/aggregate/anomaly plane; injectable so the fake-clock
        # anomaly test drives a FleetObs with a fake fetch + fake clock
        self.fleet = (
            fleet_obs
            if fleet_obs is not None
            else FleetObs(
                registry,
                registry=self.obs,
                recorder=self.recorder,
                affinity_rate_fn=self.affinity_rate,
            )
        )
        self.fleet.register()

    # --------------------------------------------------------------- route

    def prompt_tokens(self, messages: list[dict]) -> list[int]:
        """Tokenize a chat exactly as replica admission will."""
        items = [
            ChatItem(str(m["role"]), str(m["content"])) for m in messages
        ]
        prompt = self.template.generate(items, append_generation_prompt=True)
        return self.tokenizer.encode(
            prompt.content, is_start=True, add_special_tokens=True
        )

    def route(self, tokens: list[int]) -> RoutePlan:
        """Plan the candidate order for one request. The affinity target
        is ALWAYS the ring's choice — in routing="random" mode only the
        try order is shuffled, so the affinity-hit metric measures the
        same thing in both modes and the bench comparison is honest."""
        key = prefix_affinity_key(tokens, self.affinity_k)
        plan = plan_route(self.ring.order(key), self.registry.views())
        if self.routing == "random" and len(plan.candidates) > 1:
            with self._count_lock:
                n = self._n_requests
                self._n_requests += 1
            rng = random.Random(f"{self._seed}:{n}")
            plan.candidates = rng.sample(
                plan.candidates, len(plan.candidates)
            )
        elif self.routing == "affinity":
            reason = plan.spill_reason
            if reason is not None:
                self.m_spills.labels(reason=reason).inc()
                self.recorder.record(
                    "router_spill",
                    reason=reason,
                    target=plan.target,
                    candidates=list(plan.candidates),
                )
        return plan

    # -------------------------------------------------- predicted wait

    def note_shed(self, name: str, retry_after) -> None:
        """A replica shed with the given ``Retry-After`` (header string,
        int, or None): remember its self-predicted busy-until time."""
        s = _retry_after_s(retry_after)
        with self._shed_lock:
            self._shed_until[name] = time.monotonic() + s

    def shed_wait_s(self, name: str) -> float:
        """Seconds this replica predicted it stays saturated (0 when it
        never shed or the backoff already expired)."""
        with self._shed_lock:
            until = self._shed_until.get(name)
        if until is None:
            return 0.0
        return max(0.0, until - time.monotonic())

    def order_by_backoff(self, candidates: list[str]) -> list[str]:
        """Predicted-wait-aware spill order: candidates whose shed
        backoff expired keep their (affinity) order and come first;
        replicas still inside a self-predicted busy window are demoted
        to the tail, soonest-free first. Nothing is dropped — when the
        whole fleet is backing off, the least-backed-off replica is
        still tried (it may have drained early)."""
        waits = [(self.shed_wait_s(n), i, n) for i, n in enumerate(candidates)]
        free = [n for w, _, n in waits if w <= 0.0]
        busy = [n for w, i, n in sorted(waits) if w > 0.0]
        return free + busy

    def min_shed_wait_s(self) -> int | None:
        """Smallest non-expired predicted wait across the fleet — the
        honest Retry-After for an all-replicas-shed 503 (None when no
        replica is inside a backoff window)."""
        waits = [
            w for w in (self.shed_wait_s(n) for n in self.registry.names)
            if w > 0.0
        ]
        if not waits:
            return None
        return max(1, int(-(-min(waits) // 1)))

    # ------------------------------------------------------------- fleet

    def affinity_rate(self) -> float | None:
        """Cumulative affinity hit rate over all routed requests (None
        before the first request); sampled into
        ``dllama_fleet_affinity_hit_rate`` each scrape."""
        total = sum(self.m_requests.child_values().values())
        if total <= 0:
            return None
        return self.m_affinity_hits.value / total

    def health_payload(self) -> dict:
        """The router's ``/v1/health`` body. Status composes replica
        registry states with the FLEET anomaly monitor: a fleet rule
        firing (TPOT skew, failover spike, goodput drop) degrades the
        router even while every replica individually reports healthy —
        exactly the fleet-level sickness a per-replica view can't see."""
        views = self.registry.views()
        states = [v.state for v in views.values()]
        if any(s == "healthy" for s in states):
            status = "ok"
        elif any(s != "dead" for s in states):
            status = "degraded"
        else:
            status = "unavailable"
        reasons = [
            f"fleet_anomaly:{sig}"
            for sig in self.fleet.monitor.active_signals()
        ]
        if reasons and status == "ok":
            status = "degraded"
        return {
            "status": status,
            "role": "router",
            "routing": self.routing,
            "uptime_s": round(time.time() - self.start_unix, 3),
            "replicas": {name: v.state for name, v in views.items()},
            "degraded_reasons": reasons,
        }


def make_router_handler(state: RouterState):
    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet access log
            pass

        def _json(
            self, payload: dict, status: int = 200,
            retry_after: int | None = None,
        ) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(status)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Content-Type", "application/json; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header("Retry-After", str(retry_after))
            self.end_headers()
            self.wfile.write(body)

        # ------------------------------------------------------------ GET

        def do_GET(self):
            path, _, query = self.path.partition("?")
            params = parse_qs(query)
            if path == "/metrics":
                # run_refresh_hooks triggers the fleet scrape (a keyed
                # hook, obs.py), so the render below already holds fresh
                # aggregates; the replica-labelled re-export block is
                # appended after the router's own families
                state.obs.run_refresh_hooks()
                text = state.obs.render()
                fleet = state.fleet.render_fleet()
                if fleet:
                    text = text.rstrip("\n") + "\n" + fleet + "\n"
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", state.obs.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/health":
                self._json(state.health_payload())
            elif path == "/v1/fleet":
                self._json(self._fleet_payload())
            elif path == "/v1/fleet/timeline":
                self._fleet_timeline(params)
            elif path == "/v1/fleet/debug/recorder":
                self._fleet_recorder()
            elif path == "/v1/debug/series":
                self._fleet_series(params)
            elif path == "/dashboard":
                body = render_dashboard()
                self.send_response(200)
                self.send_header("Content-Type", DASHBOARD_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/models":
                self._json(
                    {
                        "object": "list",
                        "data": [
                            {
                                "id": state.model_name,
                                "object": "model",
                                "created": 0,
                                "owned_by": "user",
                            }
                        ],
                    }
                )
            elif path in ("/health", "/healthz"):
                self._json({"status": "ok"})
            else:
                self.send_error(404, "Not Found")

        def _fetch_json(self, url: str) -> dict:
            """GET a replica debug endpoint as JSON (raises OSError /
            ValueError, handled per call site — a dead replica degrades
            the merged view, never the whole response)."""
            with urllib.request.urlopen(url, timeout=10.0) as r:
                return json.loads(r.read())

        def _fleet_timeline(self, params: dict) -> None:
            """GET /v1/fleet/timeline[?request_id=] — bare: the request
            ledger's recent entries (pick a request id to stitch); with
            an id: ONE merged Chrome/Perfetto trace of the router's own
            spans plus every touched replica's fragment, pid-namespaced
            per source and rebased onto the router's epoch, so a
            failover reads as one continuous request with the gap
            attributed to the router's ``failover`` span."""
            rid = (params.get("request_id") or [None])[0]
            if rid is None:
                self._json({"recent": state.ledger.recent()})
                return
            entry = state.ledger.get(rid)
            if entry is None:
                self._json(
                    {
                        "error": {
                            "message": f"unknown request_id {rid!r} "
                            "(the ledger keeps the most recent requests)",
                        }
                    },
                    404,
                )
                return
            router_frag = state.spans.chrome_trace(
                request_id=rid, pid_prefix="router"
            )
            names = sorted(state.registry.names)
            fragments: list[tuple[str, dict]] = []
            errors: dict[str, str] = {}
            for name in entry["replicas"]:
                # stable pid namespace per replica regardless of which
                # replicas THIS request touched
                idx = names.index(name) if name in names else len(names)
                q = urlencode(
                    {
                        "request_id": rid,
                        "replica": name,
                        "pid_prefix": name,
                        "pid_base": PID_STRIDE * (idx + 1),
                    }
                )
                url = state.registry.url_of(name)
                try:
                    frag = self._fetch_json(
                        f"{url}/v1/debug/timeline?{q}"
                    )
                except (OSError, ValueError) as e:
                    errors[name] = f"{type(e).__name__}: {e}"
                    state.recorder.record(
                        "fleet_timeline_error", replica=name,
                        error=errors[name],
                    )
                    continue
                fragments.append((name, frag))
            merged = stitch_timelines(router_frag, fragments)
            merged["dllama"]["request_id"] = rid
            merged["dllama"]["trace_id"] = entry["trace_id"]
            merged["dllama"]["replicas"] = entry["replicas"]
            merged["dllama"]["failovers"] = entry["failovers"]
            if errors:
                merged["dllama"]["fetch_errors"] = errors
            self._json(merged)

        def _fleet_recorder(self) -> None:
            """GET /v1/fleet/debug/recorder — the fleet postmortem in one
            fetch: the router's flight-recorder ring (router_failover /
            router_stall / router_spill / drain / scrape events) plus
            every replica's ring (or the fetch error in its place)."""
            out: dict = {"router": state.recorder.dump(), "replicas": {}}
            for name in sorted(state.registry.names):
                url = state.registry.url_of(name)
                try:
                    out["replicas"][name] = self._fetch_json(
                        f"{url}/v1/debug/recorder"
                    )
                except (OSError, ValueError) as e:
                    out["replicas"][name] = {
                        "error": f"{type(e).__name__}: {e}"
                    }
                    state.recorder.record(
                        "fleet_recorder_error", replica=name,
                        error=f"{type(e).__name__}: {e}",
                    )
            self._json(out)

        def _fleet_series(self, params: dict) -> None:
            """GET /v1/debug/series on the router — same shape as the
            replica endpoint (the shared /dashboard JS reads either) but
            backed by the FLEET store: aggregate goodput, per-replica
            TPOT p50, skew, failover counters."""
            store = state.fleet.store
            name = (params.get("name") or [None])[0]
            if name is None:
                self._json(
                    {
                        "names": store.names(),
                        "interval_s": store.interval_s,
                        "retention_s": store.retention_s,
                        "anomaly": state.fleet.monitor.status(),
                    }
                )
                return
            try:
                window = float((params.get("window") or ["300"])[0])
            except ValueError:
                self._json({"error": {"message": "bad window"}}, 400)
                return
            result = store.query(name, window)
            if result is None:
                self._json(
                    {"error": {"message": f"no series {name!r}"}}, 404
                )
                return
            self._json(result)

        def _fleet_payload(self) -> dict:
            views = state.registry.views()
            agg = {
                "lanes_total": sum(v.lanes for v in views.values()),
                "in_flight": sum(v.in_flight for v in views.values()),
                "parked": sum(v.parked for v in views.values()),
                "max_streams": sum(
                    v.max_streams for v in views.values()
                ),
                "states": {},
            }
            for v in views.values():
                agg["states"][v.state] = agg["states"].get(v.state, 0) + 1
            return {
                "router": {
                    "routing": state.routing,
                    "affinity_k": state.affinity_k,
                    "failover_max": state.failover_max,
                    "stall_timeout_s": state.stall_timeout_s,
                    "model": state.model_name,
                },
                "aggregate": agg,
                "replicas": state.registry.snapshot(),
            }

        # ----------------------------------------------------------- POST

        def do_POST(self):
            path, _, query = self.path.partition("?")
            if path == "/v1/drain":
                self._drain(parse_qs(query))
                return
            if path != "/v1/chat/completions":
                self.send_error(404, "Not Found")
                return
            # fleet identity (ISSUE 19): minted HERE, forwarded on every
            # relay and failover re-issue, echoed back to the client —
            # the one id that stitches router spans, replica spans,
            # recorder events and trace JSONL into a single story
            rid = f"req-{uuid.uuid4().hex[:12]}"
            trace = f"trace-{uuid.uuid4().hex[:12]}"
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                messages = body.get("messages")
                if not isinstance(messages, list) or not messages:
                    raise ValueError("messages required")
                with state.spans.span(
                    "tokenize", component="router", request_id=rid
                ):
                    tokens = state.prompt_tokens(messages)
            except (ValueError, KeyError, TypeError) as e:
                state.m_requests.labels(
                    replica="none", outcome="bad_request"
                ).inc()
                self._json({"error": {"message": f"bad request: {e}"}}, 400)
                return
            with state.spans.span(
                "route_plan", component="router", request_id=rid,
                n_prompt_tokens=len(tokens),
            ) as route_h:
                plan = state.route(tokens)
                state.spans.end(
                    route_h,
                    target=plan.target,
                    candidates=list(plan.candidates),
                )
            state.ledger.open(rid, trace)
            plan.candidates = state.order_by_backoff(plan.candidates)
            if not plan.candidates:
                state.m_requests.labels(
                    replica="none", outcome="unavailable"
                ).inc()
                ra = state.min_shed_wait_s() or 2
                self._json(
                    {
                        "error": {
                            "message": "no replica available",
                            "retryable": True,
                            "retry_after_s": ra,
                        }
                    },
                    503,
                    retry_after=ra,
                )
                return
            if body.get("stream"):
                self._relay_stream(body, tokens, plan, rid, trace)
            else:
                self._relay_unary(body, plan, rid, trace)

        def _drain(self, params: dict) -> None:
            """POST /v1/drain?replica=NAME — forward the drain and stop
            routing to the replica immediately (docs/fleet.md runbook)."""
            name = (params.get("replica") or [None])[0]
            if name is None or name not in state.registry.names:
                self._json(
                    {
                        "error": {
                            "message": "replica query param required, one "
                            f"of {sorted(state.registry.names)}",
                        }
                    },
                    400,
                )
                return
            url = state.registry.url_of(name)
            try:
                req = urllib.request.Request(
                    f"{url}/v1/drain", data=b"", method="POST"
                )
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    payload = json.loads(r.read())
            except (OSError, ValueError) as e:
                state.recorder.record(
                    "router_drain_error", replica=name,
                    error=f"{type(e).__name__}: {e}",
                )
                self._json(
                    {"error": {"message": f"drain forward failed: {e}"}},
                    502,
                )
                return
            state.registry.mark_draining(name)
            state.recorder.record("router_drain", replica=name)
            payload["replica"] = name
            self._json(payload)

        # --------------------------------------------------- unary relay

        def _relay_unary(
            self, body: dict, plan: RoutePlan, rid: str, trace: str
        ) -> None:
            """Non-stream requests: whole-request retry on the next
            candidate (greedy/seeded requests reproduce; an unseeded
            sampled request re-samples — documented in docs/fleet.md)."""
            headers = self._fleet_headers(rid, trace)
            shed_ra = None  # smallest upstream Retry-After seen
            for name in plan.candidates:
                relay_h = state.spans.begin(
                    "relay", component="router", request_id=rid,
                    replica=name,
                )
                res = self._open_upstream(
                    state.registry.url_of(name), body, headers
                )
                kind = res[0]
                if kind == "refused":
                    state.registry.mark_dead(name, "connect")
                    state.m_spills.labels(reason="refused").inc()
                    state.m_requests.labels(
                        replica=name, outcome="refused"
                    ).inc()
                    state.recorder.record(
                        "router_spill", reason="refused", replica=name,
                        request_id=rid,
                    )
                    state.spans.end(relay_h, outcome="refused")
                    continue
                if kind == "stream":  # impossible for stream=False
                    res[1].close()
                    state.m_requests.labels(
                        replica=name, outcome="protocol"
                    ).inc()
                    state.spans.end(relay_h, outcome="protocol")
                    continue
                _, status, data, retry_after = res
                if status in (429, 503):
                    state.note_shed(name, retry_after)
                    ra = _retry_after_s(retry_after)
                    shed_ra = ra if shed_ra is None else min(shed_ra, ra)
                    state.m_spills.labels(reason="shed").inc()
                    state.m_requests.labels(
                        replica=name, outcome="shed"
                    ).inc()
                    state.recorder.record(
                        "router_spill", reason="shed", replica=name,
                        request_id=rid, status=status,
                    )
                    state.spans.end(relay_h, outcome="shed")
                    continue
                state.m_requests.labels(
                    replica=name,
                    outcome="ok" if status == 200 else f"http_{status}",
                ).inc()
                if name == plan.target:
                    state.m_affinity_hits.inc()
                state.ledger.touch(rid, name)
                state.spans.end(relay_h, outcome=f"http_{status}")
                self.send_response(status)
                self.send_header(
                    "Content-Type", "application/json; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(data)))
                self.send_header("x-dllama-request", rid)
                self.send_header("x-dllama-trace", trace)
                self.end_headers()
                self.wfile.write(data)
                return
            state.m_requests.labels(
                replica="none", outcome="unavailable"
            ).inc()
            # propagate the fleet's own prediction: the smallest upstream
            # Retry-After seen this request (replicas derive it from
            # predicted queue-drain time, ISSUE 20), not a constant
            ra = shed_ra if shed_ra is not None else 2
            self._json(
                {
                    "error": {
                        "message": "all replicas refused or shed",
                        "retryable": True,
                        "retry_after_s": ra,
                    }
                },
                503,
                retry_after=ra,
            )

        # -------------------------------------------------- stream relay

        def _fleet_headers(self, rid: str, trace: str) -> dict:
            """Relay headers: the trace-propagation pair plus the
            client's deadline hint (``x-dllama-deadline-ms``), forwarded
            verbatim so replica-side predictive admission sees the same
            budget on the first issue AND on failover re-issues."""
            headers = {"x-dllama-trace": trace, "x-dllama-request": rid}
            ddl = self.headers.get("x-dllama-deadline-ms")
            if ddl:
                headers["x-dllama-deadline-ms"] = ddl
            return headers

        def _open_upstream(
            self, base_url: str, req_body: dict,
            headers: dict | None = None,
        ):
            """POST to a replica. Returns one of
            ``("stream", conn, resp)`` (SSE accepted),
            ``("response", status, body_bytes, retry_after)``, or
            ``("refused", reason)`` (connect/send failure). ``headers``
            carries the trace-propagation pair on every issue AND
            re-issue, so failed-over requests keep their identity."""
            u = urlsplit(base_url)
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=state.stall_timeout_s
            )
            try:
                conn.request(
                    "POST",
                    "/v1/chat/completions",
                    json.dumps(req_body),
                    {"Content-Type": "application/json", **(headers or {})},
                )
                resp = conn.getresponse()
            except OSError as e:
                conn.close()
                return ("refused", f"{type(e).__name__}: {e}")
            ctype = resp.getheader("Content-Type") or ""
            if resp.status == 200 and "text/event-stream" in ctype:
                return ("stream", conn, resp)
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                return ("refused", f"{type(e).__name__}: {e}")
            retry_after = resp.getheader("Retry-After")
            conn.close()
            return ("response", resp.status, data, retry_after)

        def _client_chunk(self, obj: dict) -> None:
            _sse_write(self.wfile, f"data: {json.dumps(obj)}\r\n\r\n")

        def _client_done(self) -> None:
            _sse_write(self.wfile, "data: [DONE]\r\n\r\n")
            self.wfile.write(b"0\r\n\r\n")

        def _sse_headers(
            self, rid: str | None = None, trace: str | None = None
        ) -> None:
            self.send_response(200)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Content-Type", "text/event-stream; charset=utf-8"
            )
            self.send_header("Transfer-Encoding", "chunked")
            if rid is not None:
                # echo the fleet identity so clients/tests can fetch the
                # stitched timeline for the stream they just consumed
                self.send_header("x-dllama-request", rid)
            if trace is not None:
                self.send_header("x-dllama-trace", trace)
            self.end_headers()

        def _synth_delta(self, text: str) -> dict:
            """A router-synthesized catch-up chunk: the exact text the
            dead replica had consumed but not yet flushed."""
            return {
                "id": "cmpl-1",
                "object": "chat.completion.chunk",
                "created": int(time.time()),
                "model": state.model_name,
                "choices": [
                    {
                        "index": 0,
                        "finish_reason": None,
                        "delta": {"role": "assistant", "content": text},
                    }
                ],
            }

        def _relay_frames(self, resp, book: dict) -> None:
            """Relay one upstream SSE stream until ``[DONE]``, keeping
            the failover books: ``emitted`` (generated token ids),
            ``exact`` (exact consumed text via dllama_piece) and
            ``relayed`` (delta text the client has) plus ``t_last`` (the
            clock at the last relayed frame — the stall-detect span's
            retroactive start). Raises _StreamDeath on EOF / stall /
            retryable error; raises OSError if OUR client's socket
            fails."""
            while True:
                book["t_last"] = time.perf_counter()
                try:
                    line = resp.readline()
                except (
                    http.client.HTTPException,
                    ConnectionError,
                    TimeoutError,
                    OSError,
                    ValueError,
                ) as e:
                    raise _StreamDeath(
                        f"read_{type(e).__name__}"
                    ) from e
                if not line:
                    raise _StreamDeath("eof")
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                payload = line[5:].strip()
                if payload == b"[DONE]":
                    if book.get("finish") is None and "error" not in book:
                        # a stream must end with a finish chunk or an
                        # error frame; a bare [DONE] is a broken replica
                        raise _StreamDeath("no_finish")
                    return
                try:
                    obj = json.loads(payload)
                except ValueError as e:
                    raise _StreamDeath("bad_frame") from e
                if "error" in obj:
                    err = obj["error"]
                    if err.get("retryable"):
                        raise _StreamDeath(
                            f"retryable:{err.get('message', '')}"
                        )
                    # non-retryable (client-caused): forward verbatim,
                    # the stream is over
                    book["error"] = err
                    self._client_chunk({"error": err})
                    continue
                tokens = obj.pop("dllama_tokens", None)
                piece = obj.pop("dllama_piece", None)
                choice = (obj.get("choices") or [{}])[0]
                text = (choice.get("delta") or {}).get("content")
                # books BEFORE the client write: a dead client aborts
                # the whole request anyway (OSError propagates)
                if tokens:
                    book["emitted"].extend(int(t) for t in tokens)
                if piece:
                    book["exact"] += piece
                if choice.get("finish_reason"):
                    book["finish"] = choice["finish_reason"]
                self._client_chunk(obj)
                if text:
                    book["relayed"] += text

        def _relay_stream(
            self, body: dict, prompt_tokens: list[int], plan: RoutePlan,
            rid: str, trace: str,
        ) -> None:
            """Stream with mid-stream failover (the tentpole headline);
            see the module docstring for the resume contract. Every
            attempt is a router ``relay`` span; a death opens a
            ``failover`` span that stays open across the re-issue and
            ends when the catch-up delta lands from the sibling — THAT
            span is the client-visible gap, and its duration feeds
            ``dllama_router_failover_gap_seconds``."""
            book: dict = {"emitted": [], "exact": "", "relayed": ""}
            headers = self._fleet_headers(rid, trace)
            shed_ra = None  # smallest upstream Retry-After seen
            max_tokens = int(body.get("max_tokens", -1) or -1)
            started = False     # SSE headers sent to OUR client
            first_replica = None
            failovers = 0
            gap_h = None        # open failover span (death -> caught up)
            gap_t0 = None
            try:
                for name in plan.candidates:
                    resuming = bool(book["emitted"])
                    upstream = dict(body)
                    upstream["stream"] = True
                    upstream["include_tokens"] = True
                    upstream.pop("resume_tokens", None)
                    if resuming:
                        upstream["resume_tokens"] = (
                            prompt_tokens + book["emitted"]
                        )
                        upstream.pop("messages", None)
                        if max_tokens > 0:
                            upstream["max_tokens"] = max(
                                1, max_tokens - len(book["emitted"])
                            )
                    relay_h = state.spans.begin(
                        "relay", component="router", request_id=rid,
                        replica=name, resumed=resuming,
                    )
                    res = self._open_upstream(
                        state.registry.url_of(name), upstream, headers
                    )
                    kind = res[0]
                    if kind == "refused":
                        state.registry.mark_dead(name, "connect")
                        state.m_spills.labels(reason="refused").inc()
                        state.m_requests.labels(
                            replica=name, outcome="refused"
                        ).inc()
                        state.recorder.record(
                            "router_spill", reason="refused",
                            replica=name, request_id=rid,
                        )
                        state.spans.end(relay_h, outcome="refused")
                        continue
                    if kind == "response":
                        _, status, data, _ra = res
                        if status in (429, 503):
                            state.note_shed(name, _ra)
                            ra = _retry_after_s(_ra)
                            shed_ra = (
                                ra if shed_ra is None else min(shed_ra, ra)
                            )
                            state.m_spills.labels(reason="shed").inc()
                            state.m_requests.labels(
                                replica=name, outcome="shed"
                            ).inc()
                            state.recorder.record(
                                "router_spill", reason="shed",
                                replica=name, request_id=rid,
                                status=status,
                            )
                            state.spans.end(relay_h, outcome="shed")
                            continue
                        # non-retryable upstream answer (e.g. 400): if
                        # the client stream hasn't started, forward it;
                        # mid-failover it terminates the stream below
                        state.m_requests.labels(
                            replica=name, outcome=f"http_{status}"
                        ).inc()
                        state.spans.end(
                            relay_h, outcome=f"http_{status}"
                        )
                        if not started:
                            self.send_response(status)
                            self.send_header(
                                "Content-Type",
                                "application/json; charset=utf-8",
                            )
                            self.send_header(
                                "Content-Length", str(len(data))
                            )
                            self.end_headers()
                            self.wfile.write(data)
                            return
                        break
                    _, conn, resp = res
                    if first_replica is None:
                        first_replica = name
                    state.ledger.touch(rid, name)
                    if not started:
                        self._sse_headers(rid, trace)
                        started = True
                    if resuming:
                        # catch-up: exact consumed text the dead replica
                        # never flushed (its detector holdback). After
                        # this, relayed == exact and the sibling's fresh
                        # deltas append cleanly.
                        with state.spans.span(
                            "catch_up_synthesis", component="router",
                            request_id=rid, replica=name,
                        ) as catch_h:
                            gap = book["exact"][len(book["relayed"]):]
                            if gap:
                                self._client_chunk(self._synth_delta(gap))
                                book["relayed"] += gap
                            state.spans.end(
                                catch_h, catch_up_chars=len(gap)
                            )
                        # the client is whole again: close the gap span
                        # and book the recovery latency
                        state.spans.end(gap_h, to_replica=name)
                        gap_h = None
                        if gap_t0 is not None:
                            gap_s = time.perf_counter() - gap_t0
                            state.m_gap.observe(gap_s)
                            state.ledger.close_failover(rid, name, gap_s)
                            gap_t0 = None
                    try:
                        self._relay_frames(resp, book)
                    except _StreamDeath as death:
                        conn.close()
                        reason = str(death)
                        state.spans.end(
                            relay_h, outcome="died", reason=reason
                        )
                        if reason.startswith("read_Timeout"):
                            # a stall, not a crash: the socket was alive
                            # but silent past the watchdog timeout
                            state.m_stalls.inc()
                            state.recorder.record(
                                "router_stall", replica=name,
                                request_id=rid,
                                stall_timeout_s=state.stall_timeout_s,
                            )
                            # retroactive stall-detect span: it BEGAN at
                            # the last relayed frame, we only know now
                            stall_h = state.spans.begin(
                                "stall_detect", component="router",
                                request_id=rid, replica=name,
                            )
                            if (
                                stall_h is not None
                                and book.get("t_last") is not None
                            ):
                                stall_h.t0 = book["t_last"]
                            state.spans.end(stall_h)
                        state.m_failovers.inc()
                        state.m_requests.labels(
                            replica=name, outcome="died"
                        ).inc()
                        state.recorder.record(
                            "router_failover",
                            replica=name,
                            reason=reason,
                            emitted_tokens=len(book["emitted"]),
                            request_id=rid,
                            trace_id=trace,
                        )
                        state.ledger.failover(
                            rid, from_replica=name, reason=reason,
                            emitted_tokens=len(book["emitted"]),
                        )
                        gap_h = state.spans.begin(
                            "failover", component="router",
                            request_id=rid, from_replica=name,
                            reason=reason,
                            emitted_tokens=len(book["emitted"]),
                        )
                        gap_t0 = time.perf_counter()
                        failovers += 1
                        if failovers > state.failover_max:
                            break
                        continue
                    # clean end: upstream sent finish (or a
                    # non-retryable error frame) then [DONE]
                    conn.close()
                    state.spans.end(
                        relay_h,
                        outcome="error" if "error" in book else "ok",
                        relayed_tokens=len(book["emitted"]),
                    )
                    state.m_requests.labels(
                        replica=name,
                        outcome="error" if "error" in book else "ok",
                    ).inc()
                    if first_replica == plan.target:
                        state.m_affinity_hits.inc()
                    self._client_done()
                    return
                # candidates (or the failover budget) exhausted
                state.spans.end(gap_h, outcome="lost")
                gap_h = None
                state.m_requests.labels(
                    replica="none", outcome="unavailable"
                ).inc()
                if not started:
                    ra = shed_ra if shed_ra is not None else 2
                    self._json(
                        {
                            "error": {
                                "message": "all replicas refused or shed",
                                "retryable": True,
                                "retry_after_s": ra,
                            }
                        },
                        503,
                        retry_after=ra,
                    )
                    return
                self._client_chunk(
                    {
                        "error": {
                            "message": "stream lost: failover budget "
                            "exhausted",
                            "retryable": True,
                        }
                    }
                )
                self._client_done()
            except OSError:
                # OUR client went away mid-relay; the upstream replica's
                # lane notices its own socket close via cancellation
                state.m_requests.labels(
                    replica=first_replica or "none",
                    outcome="client_gone",
                ).inc()
                self.close_connection = True

    return RouterHandler


def serve_router(
    registry: ReplicaRegistry,
    tokenizer: Tokenizer,
    host: str = "127.0.0.1",
    port: int = 0,
    chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
    model_name: str = "dllama-fleet",
    affinity_k: int | None = None,
    failover_max: int | None = None,
    stall_timeout_s: float | None = None,
    routing: str = "affinity",
    seed: int = 0,
    start_poller: bool = True,
) -> ThreadingHTTPServer:
    """Build the front door. The caller runs ``serve_forever()`` (tests
    drive it in a thread); ``server_close()`` stops the health poller."""
    state = RouterState(
        registry,
        tokenizer,
        chat_template_type=chat_template_type,
        model_name=model_name,
        affinity_k=affinity_k,
        failover_max=failover_max,
        stall_timeout_s=stall_timeout_s,
        routing=routing,
        seed=seed,
    )
    registry.poll_once()  # seed states before the first request
    if start_poller:
        registry.start()
        # the fleet sampler rides the poller decision: tests that drive
        # polls synchronously also drive scrapes/samples synchronously
        # (state.fleet.sampler / scrape_once), so no background thread
        # races their assertions
        state.fleet.start()
    server = ThreadingHTTPServer((host, port), make_router_handler(state))
    server.state = state
    inner_close = server.server_close

    def _close_and_stop():
        inner_close()
        registry.stop()
        state.fleet.close()

    server.server_close = _close_and_stop
    return server


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dllama-tpu-router",
        description="Prefix-affinity fleet router (docs/fleet.md)",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9980)
    parser.add_argument(
        "--replica", action="append", required=True, metavar="NAME=URL",
        help="replica endpoint, repeatable: r0=http://127.0.0.1:9990",
    )
    parser.add_argument("--tokenizer", required=True)
    parser.add_argument(
        "--chat-template", default=None,
        choices=sorted(CHAT_TEMPLATE_NAMES),
    )
    parser.add_argument("--model-name", default="dllama-fleet")
    parser.add_argument("--affinity-k", type=int, default=None)
    parser.add_argument("--failover-max", type=int, default=None)
    parser.add_argument("--stall-timeout-s", type=float, default=None)
    parser.add_argument(
        "--routing", default="affinity", choices=("affinity", "random")
    )
    args = parser.parse_args(argv)

    replicas = {}
    for spec in args.replica:
        name, sep, url = spec.partition("=")
        if not sep or not name or not url:
            raise SystemExit(f"--replica must be NAME=URL, got {spec!r}")
        replicas[name] = url.rstrip("/")
    _, _, _, poll_s = resolve_fleet_knobs()
    registry = ReplicaRegistry(replicas, poll_interval_s=poll_s)
    tok = Tokenizer(args.tokenizer)
    ttype = (
        CHAT_TEMPLATE_NAMES[args.chat_template]
        if args.chat_template
        else ChatTemplateType.UNKNOWN
    )
    server = serve_router(
        registry,
        tok,
        host=args.host,
        port=args.port,
        chat_template_type=ttype,
        model_name=args.model_name,
        affinity_k=args.affinity_k,
        failover_max=args.failover_max,
        stall_timeout_s=args.stall_timeout_s,
        routing=args.routing,
    )
    print(
        f"Router URL: http://localhost:{server.server_address[1]}/v1/ "
        f"({len(replicas)} replicas, routing={args.routing})"
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()


if __name__ == "__main__":
    main()
