"""The fleet front door: prefix-affinity routing + mid-stream failover.

A stdlib ``ThreadingHTTPServer`` (same style as ``runtime/api_server``,
deliberately engine-free — the router process never imports jax) that
fronts N replicas:

* **Affinity.** Each ``POST /v1/chat/completions`` is tokenized ONCE at
  the router with exactly the replica's admission recipe (chat template
  with ``append_generation_prompt=True``, then ``encode(is_start=True,
  add_special_tokens=True)``); the first K token ids hash onto a
  consistent ring (:mod:`.affinity`), so repeated and shared-prefix
  prompts land on the replica whose radix tree holds their prefix.
* **Spill.** The ring order is filtered through replica health
  (:mod:`.replicas`): dead/draining/saturated siblings are skipped,
  degraded ones demoted to last resort, and a 429/503 shed or refused
  connection at request time moves to the next candidate. Every
  diversion counts in ``dllama_router_spills_total{reason}``.
* **Mid-stream failover.** Replicas stream with ``include_tokens``, so
  every SSE chunk carries the exact generated token ids
  (``dllama_tokens``) and their raw decoded text (``dllama_piece``).
  When a replica dies mid-stream (EOF, stall past the watchdog read
  timeout, or an in-stream retryable error), the router first emits the
  catch-up delta — the exact text consumed but still held back by the
  dead replica's EOS detector — then re-issues the request to the next
  sibling as ``resume_tokens`` = prompt tokens + emitted tokens. The
  sibling's recovery-admission path (radix re-match + chunked
  re-prefill) continues the stream byte-identically under greedy
  decoding, on the SAME client connection. docs/fleet.md spells out the
  contract and its two edge cases (stop strings and incomplete UTF-8
  spanning the boundary).

Knobs resolve CLI-beats-env-beats-default via the ``DLLAMA_FLEET_*``
family: ``DLLAMA_FLEET_AFFINITY_K``, ``DLLAMA_FLEET_FAILOVER_MAX``,
``DLLAMA_FLEET_STALL_S``, ``DLLAMA_FLEET_POLL_S``.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..obs.metrics import get_registry
from ..obs.recorder import get_recorder
from ..tokenizer import (
    CHAT_TEMPLATE_NAMES,
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    Tokenizer,
)
from .affinity import (
    DEFAULT_AFFINITY_K,
    HashRing,
    RoutePlan,
    plan_route,
    prefix_affinity_key,
)
from .replicas import ReplicaRegistry

DEFAULT_FAILOVER_MAX = 3
DEFAULT_STALL_S = 120.0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def resolve_fleet_knobs(
    affinity_k: int | None = None,
    failover_max: int | None = None,
    stall_timeout_s: float | None = None,
    poll_interval_s: float | None = None,
) -> tuple[int, int, float, float]:
    """Router knob resolution: explicit value (CLI flag / constructor)
    beats the ``DLLAMA_FLEET_*`` env knob beats the default — the same
    ladder as the engine's lane/stream knobs."""
    k = (
        int(affinity_k)
        if affinity_k is not None
        else int(_env_float("DLLAMA_FLEET_AFFINITY_K", DEFAULT_AFFINITY_K))
    )
    fmax = (
        int(failover_max)
        if failover_max is not None
        else int(_env_float("DLLAMA_FLEET_FAILOVER_MAX", DEFAULT_FAILOVER_MAX))
    )
    stall = (
        float(stall_timeout_s)
        if stall_timeout_s is not None
        else _env_float("DLLAMA_FLEET_STALL_S", DEFAULT_STALL_S)
    )
    poll = (
        float(poll_interval_s)
        if poll_interval_s is not None
        else _env_float("DLLAMA_FLEET_POLL_S", 2.0)
    )
    if k <= 0:
        raise ValueError(f"affinity k must be positive, got {k}")
    return k, max(0, fmax), stall, poll


def _sse_write(wfile, data: str) -> None:
    """One HTTP-chunked SSE frame (mirror of the replica server's)."""
    raw = data.encode("utf-8")
    wfile.write(f"{len(raw):x}\r\n".encode() + raw + b"\r\n")


class _StreamDeath(Exception):
    """An upstream replica's SSE stream died recoverably mid-flight:
    EOF / broken chunking, a read stall past the watchdog timeout, or an
    in-stream retryable error frame. The relay fails over."""


class RouterState:
    """Shared router state: registry + ring + tokenizer + metrics."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        tokenizer: Tokenizer,
        chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
        model_name: str = "dllama-fleet",
        affinity_k: int | None = None,
        failover_max: int | None = None,
        stall_timeout_s: float | None = None,
        routing: str = "affinity",
        seed: int = 0,
    ):
        if routing not in ("affinity", "random"):
            raise ValueError(f"unknown routing mode {routing!r}")
        self.registry = registry
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.routing = routing
        self.start_unix = time.time()
        k, fmax, stall, _ = resolve_fleet_knobs(
            affinity_k, failover_max, stall_timeout_s
        )
        self.affinity_k = k
        self.failover_max = fmax
        self.stall_timeout_s = stall
        # the router's prompt rendering MUST mirror the replica's
        # admission path token-for-token — the affinity key hashes the
        # very ids the replica's radix tree stores (tests cross-check
        # against the replica's reported usage.prompt_tokens)
        stops = [
            tokenizer.vocab[t].decode("utf-8", "replace")
            for t in tokenizer.eos_token_ids
        ]
        self.template = ChatTemplateGenerator(
            chat_template_type,
            tokenizer.chat_template,
            stops[0] if stops else "",
        )
        self.ring = HashRing(registry.names)
        # deterministic per-request RNG stream for routing="random" (the
        # bench's affinity-off baseline): string seeding is stable across
        # processes, unlike hash()-seeded tuples
        self._seed = seed
        self._n_requests = 0
        self._count_lock = threading.Lock()
        self.obs = get_registry()
        self.recorder = get_recorder()
        self.m_requests = self.obs.counter(
            "dllama_router_requests_total",
            "Router requests by serving replica and outcome (ok, error, "
            "shed, refused, died, client_gone, unavailable, ...).",
            labelnames=("replica", "outcome"),
        )
        self.m_failovers = self.obs.counter(
            "dllama_router_failovers_total",
            "Mid-stream failovers: a replica's SSE stream died and the "
            "router resumed it on a sibling via resume_tokens.",
        )
        self.m_affinity_hits = self.obs.counter(
            "dllama_router_affinity_hits_total",
            "Requests served by their prefix-affinity target replica "
            "(first streamed-from replica == consistent-hash target).",
        )
        self.m_spills = self.obs.counter(
            "dllama_router_spills_total",
            "Requests diverted off their affinity target by reason "
            "(dead, draining, saturated, degraded, shed, refused).",
            labelnames=("reason",),
        )

    # --------------------------------------------------------------- route

    def prompt_tokens(self, messages: list[dict]) -> list[int]:
        """Tokenize a chat exactly as replica admission will."""
        items = [
            ChatItem(str(m["role"]), str(m["content"])) for m in messages
        ]
        prompt = self.template.generate(items, append_generation_prompt=True)
        return self.tokenizer.encode(
            prompt.content, is_start=True, add_special_tokens=True
        )

    def route(self, tokens: list[int]) -> RoutePlan:
        """Plan the candidate order for one request. The affinity target
        is ALWAYS the ring's choice — in routing="random" mode only the
        try order is shuffled, so the affinity-hit metric measures the
        same thing in both modes and the bench comparison is honest."""
        key = prefix_affinity_key(tokens, self.affinity_k)
        plan = plan_route(self.ring.order(key), self.registry.views())
        if self.routing == "random" and len(plan.candidates) > 1:
            with self._count_lock:
                n = self._n_requests
                self._n_requests += 1
            rng = random.Random(f"{self._seed}:{n}")
            plan.candidates = rng.sample(
                plan.candidates, len(plan.candidates)
            )
        elif self.routing == "affinity":
            reason = plan.spill_reason
            if reason is not None:
                self.m_spills.labels(reason=reason).inc()
        return plan


def make_router_handler(state: RouterState):
    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet access log
            pass

        def _json(
            self, payload: dict, status: int = 200,
            retry_after: int | None = None,
        ) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(status)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Content-Type", "application/json; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header("Retry-After", str(retry_after))
            self.end_headers()
            self.wfile.write(body)

        # ------------------------------------------------------------ GET

        def do_GET(self):
            path = self.path.partition("?")[0]
            if path == "/metrics":
                state.obs.run_refresh_hooks()
                body = state.obs.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", state.obs.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/health":
                self._json(self._fleet_health())
            elif path == "/v1/fleet":
                self._json(self._fleet_payload())
            elif path == "/v1/models":
                self._json(
                    {
                        "object": "list",
                        "data": [
                            {
                                "id": state.model_name,
                                "object": "model",
                                "created": 0,
                                "owned_by": "user",
                            }
                        ],
                    }
                )
            elif path in ("/health", "/healthz"):
                self._json({"status": "ok"})
            else:
                self.send_error(404, "Not Found")

        def _fleet_health(self) -> dict:
            views = state.registry.views()
            states = [v.state for v in views.values()]
            if any(s == "healthy" for s in states):
                status = "ok"
            elif any(s != "dead" for s in states):
                status = "degraded"
            else:
                status = "unavailable"
            return {
                "status": status,
                "role": "router",
                "routing": state.routing,
                "uptime_s": round(time.time() - state.start_unix, 3),
                "replicas": {name: v.state for name, v in views.items()},
            }

        def _fleet_payload(self) -> dict:
            views = state.registry.views()
            agg = {
                "lanes_total": sum(v.lanes for v in views.values()),
                "in_flight": sum(v.in_flight for v in views.values()),
                "parked": sum(v.parked for v in views.values()),
                "max_streams": sum(
                    v.max_streams for v in views.values()
                ),
                "states": {},
            }
            for v in views.values():
                agg["states"][v.state] = agg["states"].get(v.state, 0) + 1
            return {
                "router": {
                    "routing": state.routing,
                    "affinity_k": state.affinity_k,
                    "failover_max": state.failover_max,
                    "stall_timeout_s": state.stall_timeout_s,
                    "model": state.model_name,
                },
                "aggregate": agg,
                "replicas": state.registry.snapshot(),
            }

        # ----------------------------------------------------------- POST

        def do_POST(self):
            path, _, query = self.path.partition("?")
            if path == "/v1/drain":
                self._drain(parse_qs(query))
                return
            if path != "/v1/chat/completions":
                self.send_error(404, "Not Found")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                messages = body.get("messages")
                if not isinstance(messages, list) or not messages:
                    raise ValueError("messages required")
                tokens = state.prompt_tokens(messages)
            except (ValueError, KeyError, TypeError) as e:
                state.m_requests.labels(
                    replica="none", outcome="bad_request"
                ).inc()
                self._json({"error": {"message": f"bad request: {e}"}}, 400)
                return
            plan = state.route(tokens)
            if not plan.candidates:
                state.m_requests.labels(
                    replica="none", outcome="unavailable"
                ).inc()
                self._json(
                    {
                        "error": {
                            "message": "no replica available",
                            "retryable": True,
                            "retry_after_s": 2,
                        }
                    },
                    503,
                    retry_after=2,
                )
                return
            if body.get("stream"):
                self._relay_stream(body, tokens, plan)
            else:
                self._relay_unary(body, plan)

        def _drain(self, params: dict) -> None:
            """POST /v1/drain?replica=NAME — forward the drain and stop
            routing to the replica immediately (docs/fleet.md runbook)."""
            name = (params.get("replica") or [None])[0]
            if name is None or name not in state.registry.names:
                self._json(
                    {
                        "error": {
                            "message": "replica query param required, one "
                            f"of {sorted(state.registry.names)}",
                        }
                    },
                    400,
                )
                return
            url = state.registry.url_of(name)
            try:
                req = urllib.request.Request(
                    f"{url}/v1/drain", data=b"", method="POST"
                )
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    payload = json.loads(r.read())
            except (OSError, ValueError) as e:
                state.recorder.record(
                    "router_drain_error", replica=name,
                    error=f"{type(e).__name__}: {e}",
                )
                self._json(
                    {"error": {"message": f"drain forward failed: {e}"}},
                    502,
                )
                return
            state.registry.mark_draining(name)
            state.recorder.record("router_drain", replica=name)
            payload["replica"] = name
            self._json(payload)

        # --------------------------------------------------- unary relay

        def _relay_unary(self, body: dict, plan: RoutePlan) -> None:
            """Non-stream requests: whole-request retry on the next
            candidate (greedy/seeded requests reproduce; an unseeded
            sampled request re-samples — documented in docs/fleet.md)."""
            for name in plan.candidates:
                res = self._open_upstream(
                    state.registry.url_of(name), body
                )
                kind = res[0]
                if kind == "refused":
                    state.registry.mark_dead(name, "connect")
                    state.m_spills.labels(reason="refused").inc()
                    state.m_requests.labels(
                        replica=name, outcome="refused"
                    ).inc()
                    continue
                if kind == "stream":  # impossible for stream=False
                    res[1].close()
                    state.m_requests.labels(
                        replica=name, outcome="protocol"
                    ).inc()
                    continue
                _, status, data, retry_after = res
                if status in (429, 503):
                    state.m_spills.labels(reason="shed").inc()
                    state.m_requests.labels(
                        replica=name, outcome="shed"
                    ).inc()
                    continue
                state.m_requests.labels(
                    replica=name,
                    outcome="ok" if status == 200 else f"http_{status}",
                ).inc()
                if name == plan.target:
                    state.m_affinity_hits.inc()
                self.send_response(status)
                self.send_header(
                    "Content-Type", "application/json; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            state.m_requests.labels(
                replica="none", outcome="unavailable"
            ).inc()
            self._json(
                {
                    "error": {
                        "message": "all replicas refused or shed",
                        "retryable": True,
                        "retry_after_s": 2,
                    }
                },
                503,
                retry_after=2,
            )

        # -------------------------------------------------- stream relay

        def _open_upstream(self, base_url: str, req_body: dict):
            """POST to a replica. Returns one of
            ``("stream", conn, resp)`` (SSE accepted),
            ``("response", status, body_bytes, retry_after)``, or
            ``("refused", reason)`` (connect/send failure)."""
            u = urlsplit(base_url)
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=state.stall_timeout_s
            )
            try:
                conn.request(
                    "POST",
                    "/v1/chat/completions",
                    json.dumps(req_body),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
            except OSError as e:
                conn.close()
                return ("refused", f"{type(e).__name__}: {e}")
            ctype = resp.getheader("Content-Type") or ""
            if resp.status == 200 and "text/event-stream" in ctype:
                return ("stream", conn, resp)
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                return ("refused", f"{type(e).__name__}: {e}")
            retry_after = resp.getheader("Retry-After")
            conn.close()
            return ("response", resp.status, data, retry_after)

        def _client_chunk(self, obj: dict) -> None:
            _sse_write(self.wfile, f"data: {json.dumps(obj)}\r\n\r\n")

        def _client_done(self) -> None:
            _sse_write(self.wfile, "data: [DONE]\r\n\r\n")
            self.wfile.write(b"0\r\n\r\n")

        def _sse_headers(self) -> None:
            self.send_response(200)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Content-Type", "text/event-stream; charset=utf-8"
            )
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

        def _synth_delta(self, text: str) -> dict:
            """A router-synthesized catch-up chunk: the exact text the
            dead replica had consumed but not yet flushed."""
            return {
                "id": "cmpl-1",
                "object": "chat.completion.chunk",
                "created": int(time.time()),
                "model": state.model_name,
                "choices": [
                    {
                        "index": 0,
                        "finish_reason": None,
                        "delta": {"role": "assistant", "content": text},
                    }
                ],
            }

        def _relay_frames(self, resp, book: dict) -> None:
            """Relay one upstream SSE stream until ``[DONE]``, keeping
            the failover books: ``emitted`` (generated token ids),
            ``exact`` (exact consumed text via dllama_piece) and
            ``relayed`` (delta text the client has). Raises _StreamDeath
            on EOF / stall / retryable error; raises OSError if OUR
            client's socket fails."""
            while True:
                try:
                    line = resp.readline()
                except (
                    http.client.HTTPException,
                    ConnectionError,
                    TimeoutError,
                    OSError,
                    ValueError,
                ) as e:
                    raise _StreamDeath(
                        f"read_{type(e).__name__}"
                    ) from e
                if not line:
                    raise _StreamDeath("eof")
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                payload = line[5:].strip()
                if payload == b"[DONE]":
                    if book.get("finish") is None and "error" not in book:
                        # a stream must end with a finish chunk or an
                        # error frame; a bare [DONE] is a broken replica
                        raise _StreamDeath("no_finish")
                    return
                try:
                    obj = json.loads(payload)
                except ValueError as e:
                    raise _StreamDeath("bad_frame") from e
                if "error" in obj:
                    err = obj["error"]
                    if err.get("retryable"):
                        raise _StreamDeath(
                            f"retryable:{err.get('message', '')}"
                        )
                    # non-retryable (client-caused): forward verbatim,
                    # the stream is over
                    book["error"] = err
                    self._client_chunk({"error": err})
                    continue
                tokens = obj.pop("dllama_tokens", None)
                piece = obj.pop("dllama_piece", None)
                choice = (obj.get("choices") or [{}])[0]
                text = (choice.get("delta") or {}).get("content")
                # books BEFORE the client write: a dead client aborts
                # the whole request anyway (OSError propagates)
                if tokens:
                    book["emitted"].extend(int(t) for t in tokens)
                if piece:
                    book["exact"] += piece
                if choice.get("finish_reason"):
                    book["finish"] = choice["finish_reason"]
                self._client_chunk(obj)
                if text:
                    book["relayed"] += text

        def _relay_stream(
            self, body: dict, prompt_tokens: list[int], plan: RoutePlan
        ) -> None:
            """Stream with mid-stream failover (the tentpole headline);
            see the module docstring for the resume contract."""
            book: dict = {"emitted": [], "exact": "", "relayed": ""}
            max_tokens = int(body.get("max_tokens", -1) or -1)
            started = False     # SSE headers sent to OUR client
            first_replica = None
            failovers = 0
            try:
                for name in plan.candidates:
                    resuming = bool(book["emitted"])
                    upstream = dict(body)
                    upstream["stream"] = True
                    upstream["include_tokens"] = True
                    upstream.pop("resume_tokens", None)
                    if resuming:
                        upstream["resume_tokens"] = (
                            prompt_tokens + book["emitted"]
                        )
                        upstream.pop("messages", None)
                        if max_tokens > 0:
                            upstream["max_tokens"] = max(
                                1, max_tokens - len(book["emitted"])
                            )
                    res = self._open_upstream(
                        state.registry.url_of(name), upstream
                    )
                    kind = res[0]
                    if kind == "refused":
                        state.registry.mark_dead(name, "connect")
                        state.m_spills.labels(reason="refused").inc()
                        state.m_requests.labels(
                            replica=name, outcome="refused"
                        ).inc()
                        continue
                    if kind == "response":
                        _, status, data, _ra = res
                        if status in (429, 503):
                            state.m_spills.labels(reason="shed").inc()
                            state.m_requests.labels(
                                replica=name, outcome="shed"
                            ).inc()
                            continue
                        # non-retryable upstream answer (e.g. 400): if
                        # the client stream hasn't started, forward it;
                        # mid-failover it terminates the stream below
                        state.m_requests.labels(
                            replica=name, outcome=f"http_{status}"
                        ).inc()
                        if not started:
                            self.send_response(status)
                            self.send_header(
                                "Content-Type",
                                "application/json; charset=utf-8",
                            )
                            self.send_header(
                                "Content-Length", str(len(data))
                            )
                            self.end_headers()
                            self.wfile.write(data)
                            return
                        break
                    _, conn, resp = res
                    if first_replica is None:
                        first_replica = name
                    if not started:
                        self._sse_headers()
                        started = True
                    if resuming:
                        # catch-up: exact consumed text the dead replica
                        # never flushed (its detector holdback). After
                        # this, relayed == exact and the sibling's fresh
                        # deltas append cleanly.
                        gap = book["exact"][len(book["relayed"]):]
                        if gap:
                            self._client_chunk(self._synth_delta(gap))
                            book["relayed"] += gap
                    try:
                        self._relay_frames(resp, book)
                    except _StreamDeath as death:
                        conn.close()
                        state.m_failovers.inc()
                        state.m_requests.labels(
                            replica=name, outcome="died"
                        ).inc()
                        state.recorder.record(
                            "router_failover",
                            replica=name,
                            reason=str(death),
                            emitted_tokens=len(book["emitted"]),
                        )
                        failovers += 1
                        if failovers > state.failover_max:
                            break
                        continue
                    # clean end: upstream sent finish (or a
                    # non-retryable error frame) then [DONE]
                    conn.close()
                    state.m_requests.labels(
                        replica=name,
                        outcome="error" if "error" in book else "ok",
                    ).inc()
                    if first_replica == plan.target:
                        state.m_affinity_hits.inc()
                    self._client_done()
                    return
                # candidates (or the failover budget) exhausted
                state.m_requests.labels(
                    replica="none", outcome="unavailable"
                ).inc()
                if not started:
                    self._json(
                        {
                            "error": {
                                "message": "all replicas refused or shed",
                                "retryable": True,
                                "retry_after_s": 2,
                            }
                        },
                        503,
                        retry_after=2,
                    )
                    return
                self._client_chunk(
                    {
                        "error": {
                            "message": "stream lost: failover budget "
                            "exhausted",
                            "retryable": True,
                        }
                    }
                )
                self._client_done()
            except OSError:
                # OUR client went away mid-relay; the upstream replica's
                # lane notices its own socket close via cancellation
                state.m_requests.labels(
                    replica=first_replica or "none",
                    outcome="client_gone",
                ).inc()
                self.close_connection = True

    return RouterHandler


def serve_router(
    registry: ReplicaRegistry,
    tokenizer: Tokenizer,
    host: str = "127.0.0.1",
    port: int = 0,
    chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
    model_name: str = "dllama-fleet",
    affinity_k: int | None = None,
    failover_max: int | None = None,
    stall_timeout_s: float | None = None,
    routing: str = "affinity",
    seed: int = 0,
    start_poller: bool = True,
) -> ThreadingHTTPServer:
    """Build the front door. The caller runs ``serve_forever()`` (tests
    drive it in a thread); ``server_close()`` stops the health poller."""
    state = RouterState(
        registry,
        tokenizer,
        chat_template_type=chat_template_type,
        model_name=model_name,
        affinity_k=affinity_k,
        failover_max=failover_max,
        stall_timeout_s=stall_timeout_s,
        routing=routing,
        seed=seed,
    )
    registry.poll_once()  # seed states before the first request
    if start_poller:
        registry.start()
    server = ThreadingHTTPServer((host, port), make_router_handler(state))
    server.state = state
    inner_close = server.server_close

    def _close_and_stop():
        inner_close()
        registry.stop()

    server.server_close = _close_and_stop
    return server


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dllama-tpu-router",
        description="Prefix-affinity fleet router (docs/fleet.md)",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9980)
    parser.add_argument(
        "--replica", action="append", required=True, metavar="NAME=URL",
        help="replica endpoint, repeatable: r0=http://127.0.0.1:9990",
    )
    parser.add_argument("--tokenizer", required=True)
    parser.add_argument(
        "--chat-template", default=None,
        choices=sorted(CHAT_TEMPLATE_NAMES),
    )
    parser.add_argument("--model-name", default="dllama-fleet")
    parser.add_argument("--affinity-k", type=int, default=None)
    parser.add_argument("--failover-max", type=int, default=None)
    parser.add_argument("--stall-timeout-s", type=float, default=None)
    parser.add_argument(
        "--routing", default="affinity", choices=("affinity", "random")
    )
    args = parser.parse_args(argv)

    replicas = {}
    for spec in args.replica:
        name, sep, url = spec.partition("=")
        if not sep or not name or not url:
            raise SystemExit(f"--replica must be NAME=URL, got {spec!r}")
        replicas[name] = url.rstrip("/")
    _, _, _, poll_s = resolve_fleet_knobs()
    registry = ReplicaRegistry(replicas, poll_interval_s=poll_s)
    tok = Tokenizer(args.tokenizer)
    ttype = (
        CHAT_TEMPLATE_NAMES[args.chat_template]
        if args.chat_template
        else ChatTemplateType.UNKNOWN
    )
    server = serve_router(
        registry,
        tok,
        host=args.host,
        port=args.port,
        chat_template_type=ttype,
        model_name=args.model_name,
        affinity_k=args.affinity_k,
        failover_max=args.failover_max,
        stall_timeout_s=args.stall_timeout_s,
        routing=args.routing,
    )
    print(
        f"Router URL: http://localhost:{server.server_address[1]}/v1/ "
        f"({len(replicas)} replicas, routing={args.routing})"
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()


if __name__ == "__main__":
    main()
