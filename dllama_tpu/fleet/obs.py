"""Fleet observability plane (ISSUE 19): the router-side half of the
fleet's measurement substrate.

Three pieces, all engine-free (the router process never imports jax):

* **Metrics re-export + aggregation.** :class:`FleetObs` scrapes every
  replica's ``/metrics`` text, re-exports it with a ``replica`` label
  injected into each series (:func:`relabel_prom_text`), and folds fleet
  aggregates into gauges on the router registry: aggregate goodput
  (sum of per-replica 1-minute SLO goodput), per-replica TPOT p50
  (computed from the scraped histogram buckets,
  :func:`histogram_quantile`), the TPOT skew across replicas, the
  router's affinity hit rate, and replica counts by state. A
  router-side :class:`~dllama_tpu.obs.timeseries.SeriesStore` +
  ``MetricsSampler`` samples those aggregates; an
  :class:`~dllama_tpu.obs.anomaly.AnomalyMonitor` over
  :func:`build_fleet_rules` (TPOT skew, failover-rate spike,
  fleet-goodput drop) feeds the router's ``/v1/health``
  ``degraded_reasons``.
* **Timeline stitching.** :func:`stitch_timelines` merges the router's
  own Chrome-trace fragment with per-replica ``/v1/debug/timeline``
  fragments into ONE Perfetto-loadable trace: each fragment arrives
  pre-namespaced (``pid_prefix``/``pid_base``, obs/spans.py) and is
  rebased onto the router's epoch via each fragment's
  ``dllama.epoch_unix``, so a mid-stream failover renders as a single
  continuous request across processes with the router's ``failover``
  span attributing the gap.
* **Request ledger.** :class:`RequestLedger` remembers, per router-
  minted request id, the trace id, which replicas served it and every
  failover hop — ``GET /v1/fleet/timeline?request_id=`` uses it to know
  which replicas to ask for fragments.

Scrape re-entrancy: the scrape runs as a keyed registry refresh hook, so
BOTH the router's ``/metrics`` handler and the fleet sampler trigger it.
In the in-process fleet the registry is process-global — a replica
scrape would recurse into the hook — so the hook takes a non-blocking
lock (inner triggers no-op) and throttles to the sampling interval.

Knobs (env, ``DLLAMA_FLEET_OBS_*`` family): ``DLLAMA_FLEET_OBS_INTERVAL_S``
(scrape/sample cadence, default 1 s), ``DLLAMA_FLEET_OBS_RETENTION_S``
(fleet series retention, default 1 h), ``DLLAMA_FLEET_OBS_LEDGER``
(request-ledger capacity, default 512).
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
import urllib.request
from collections import OrderedDict
from typing import Callable

from ..analysis.lockwatch import make_lock
from ..obs.anomaly import AnomalyMonitor, AnomalyRule, level, slope
from ..obs.metrics import MetricsRegistry, get_registry
from ..obs.recorder import FlightRecorder, get_recorder
from ..obs.slo import GOODPUT_METRIC
from ..obs.timeseries import MetricsSampler, SeriesStore
from .replicas import ReplicaRegistry

DEFAULT_OBS_INTERVAL_S = 1.0
DEFAULT_OBS_RETENTION_S = 3600.0
DEFAULT_LEDGER_CAP = 512

# pid namespace stride per stitched fragment: the router keeps pid_base
# 0, replica i gets 100*(i+1) — far above the component pid table
PID_STRIDE = 100


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


def resolve_fleet_obs_knobs(
    interval_s: float | None = None,
    retention_s: float | None = None,
    ledger_cap: int | None = None,
) -> tuple[float, float, int]:
    """Fleet-obs knob resolution, explicit beats ``DLLAMA_FLEET_OBS_*``
    env beats default (the same ladder as the router's fleet knobs)."""
    if interval_s is None:
        interval_s = _env_float(
            "DLLAMA_FLEET_OBS_INTERVAL_S", DEFAULT_OBS_INTERVAL_S
        )
    if retention_s is None:
        retention_s = _env_float(
            "DLLAMA_FLEET_OBS_RETENTION_S", DEFAULT_OBS_RETENTION_S
        )
    if ledger_cap is None:
        ledger_cap = _env_int("DLLAMA_FLEET_OBS_LEDGER", DEFAULT_LEDGER_CAP)
    if interval_s <= 0:
        raise ValueError(f"fleet obs interval must be positive: {interval_s}")
    if ledger_cap < 1:
        raise ValueError(f"fleet obs ledger cap must be >= 1: {ledger_cap}")
    return float(interval_s), float(retention_s), int(ledger_cap)


# ---------------------------------------------------------------------------
# Prometheus text parsing / relabeling
# ---------------------------------------------------------------------------

_SERIES_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prom_text(text: str) -> list[tuple[str, dict[str, str], float]]:
    """Parse Prometheus exposition text into ``(name, labels, value)``
    triples; comment lines and malformed values are skipped (a replica
    mid-restart must degrade the scrape, never raise)."""
    out: list[tuple[str, dict[str, str], float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SERIES_LINE.match(line)
        if m is None:
            continue
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels = (
            {k: v for k, v in _LABEL.findall(labels_raw)}
            if labels_raw
            else {}
        )
        out.append((name, labels, value))
    return out


def relabel_prom_text(
    text: str, replica: str, skip_prefixes: tuple[str, ...] = ()
) -> str:
    """Re-emit one replica's scrape with ``replica="<name>"`` injected as
    the first label of every series. Comment lines (HELP/TYPE) are
    dropped — N re-exported sections would otherwise repeat them per
    replica, which Prometheus rejects as duplicate metadata."""
    out: list[str] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SERIES_LINE.match(line)
        if m is None:
            continue
        name, labels_raw, value_raw = m.groups()
        if name.startswith(skip_prefixes):
            continue
        inner = labels_raw[1:-1] if labels_raw else ""
        merged = f'replica="{replica}"' + ("," + inner if inner else "")
        out.append(f"{name}{{{merged}}} {value_raw}")
    return "\n".join(out)


def histogram_quantile(
    series: list[tuple[str, dict[str, str], float]],
    name: str,
    q: float,
) -> float | None:
    """PromQL-style ``histogram_quantile`` over parsed ``_bucket`` lines
    of one (unlabelled beyond ``le``) histogram: linear interpolation
    inside the target cumulative bucket. None when the histogram is
    absent or empty."""
    buckets: list[tuple[float, float]] = []
    for sname, labels, value in series:
        if sname != f"{name}_bucket" or "le" not in labels:
            continue
        le = labels["le"]
        bound = math.inf if le in ("+Inf", "inf") else float(le)
        buckets.append((bound, value))
    if not buckets:
        return None
    buckets.sort(key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= target:
            if math.isinf(bound):
                # everything above the last finite bound: report it
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return buckets[-1][0] if math.isfinite(buckets[-1][0]) else prev_bound


# ---------------------------------------------------------------------------
# timeline stitching
# ---------------------------------------------------------------------------


def stitch_timelines(
    router_trace: dict, fragments: list[tuple[str, dict]]
) -> dict:
    """Merge the router's Chrome-trace fragment with per-replica
    fragments into one trace. Each fragment must already be namespaced
    (fetched with ``pid_prefix``/``pid_base``); this function only
    rebases timestamps — every fragment's ``ts`` values are seconds
    since ITS tracker's epoch, so the per-fragment ``dllama.epoch_unix``
    anchors translate them all onto the router's timebase."""
    router_meta = router_trace.get("dllama") or {}
    router_epoch = float(router_meta.get("epoch_unix") or 0.0)
    events: list[dict] = list(router_trace.get("traceEvents") or [])
    sources = {
        "router": sum(1 for e in events if e.get("ph") == "X"),
    }
    for name, frag in fragments:
        frag_meta = frag.get("dllama") or {}
        frag_epoch = float(frag_meta.get("epoch_unix") or router_epoch)
        shift_us = (frag_epoch - router_epoch) * 1e6
        n_x = 0
        for ev in frag.get("traceEvents") or []:
            ev = dict(ev)
            if ev.get("ph") == "X":
                n_x += 1
                ev["ts"] = round(float(ev.get("ts") or 0.0) + shift_us, 3)
            events.append(ev)
        sources[name] = n_x
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "dllama": {
            "epoch_unix": router_epoch,
            "n_spans": sum(sources.values()),
            "sources": sources,
        },
    }


# ---------------------------------------------------------------------------
# request ledger
# ---------------------------------------------------------------------------


class RequestLedger:
    """Bounded map of router-minted request id -> fleet routing history
    (trace id, replicas touched in order, failover hops). The stitcher
    reads it to know which replicas hold timeline fragments; old entries
    fall off FIFO so a long-lived router never grows."""

    def __init__(self, capacity: int = DEFAULT_LEDGER_CAP) -> None:
        self.capacity = int(capacity)
        self._lock = make_lock("fleet.ledger")
        self._entries: OrderedDict[str, dict] = OrderedDict()

    def open(self, request_id: str, trace_id: str) -> None:
        with self._lock:
            self._entries[request_id] = {
                "trace_id": trace_id,
                "replicas": [],
                "failovers": [],
            }
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def touch(self, request_id: str, replica: str) -> None:
        """Record that ``replica`` is now serving the request (appended
        only on change, so a retry loop doesn't spam the list)."""
        with self._lock:
            e = self._entries.get(request_id)
            if e is None:
                return
            if not e["replicas"] or e["replicas"][-1] != replica:
                e["replicas"].append(replica)

    def failover(
        self,
        request_id: str,
        from_replica: str,
        reason: str,
        emitted_tokens: int,
        gap_s: float | None = None,
        to_replica: str | None = None,
    ) -> None:
        with self._lock:
            e = self._entries.get(request_id)
            if e is None:
                return
            e["failovers"].append({
                "from": from_replica,
                "to": to_replica,
                "reason": reason,
                "emitted_tokens": emitted_tokens,
                "gap_s": gap_s,
            })

    def close_failover(
        self, request_id: str, to_replica: str, gap_s: float
    ) -> None:
        """Attribute the open (last) failover hop once the sibling
        stream is live: where it landed and how long the gap was."""
        with self._lock:
            e = self._entries.get(request_id)
            if e is None or not e["failovers"]:
                return
            last = e["failovers"][-1]
            if last["to"] is None:
                last["to"] = to_replica
                last["gap_s"] = round(gap_s, 6)

    def get(self, request_id: str) -> dict | None:
        with self._lock:
            e = self._entries.get(request_id)
            if e is None:
                return None
            return {
                "trace_id": e["trace_id"],
                "replicas": list(e["replicas"]),
                "failovers": [dict(f) for f in e["failovers"]],
            }

    def recent(self, n: int = 50) -> list[dict]:
        with self._lock:
            items = list(self._entries.items())[-n:]
        return [
            {
                "request_id": rid,
                "trace_id": e["trace_id"],
                "replicas": list(e["replicas"]),
                "n_failovers": len(e["failovers"]),
            }
            for rid, e in reversed(items)
        ]


# ---------------------------------------------------------------------------
# fleet anomaly rules
# ---------------------------------------------------------------------------

# the fleet aggregate series the default fleet rules watch (the router's
# postmortem evidence window, mirroring obs/anomaly.DEFAULT_SIGNAL_SERIES)
FLEET_SIGNAL_SERIES = (
    "dllama_fleet_goodput_tokens_per_s",
    "dllama_fleet_tpot_skew_ms",
    "dllama_router_failovers_total",
)


def build_fleet_rules(store: SeriesStore) -> list[AnomalyRule]:
    """The fleet-level rule set over the aggregates the scrape just
    folded into the router's series store:

    * ``fleet_tpot_skew`` — one replica's TPOT p50 pulling away from its
      siblings (ms of spread), the canonical sick-replica signature a
      per-replica monitor can't see;
    * ``fleet_failover_rate`` — the failover counter's per-tick slope
      spiking (replica deaths are rare; a burst is an incident);
    * ``fleet_goodput`` — the fleet's aggregate SLO-met tokens/s
      dropping far below baseline while under load.

    Guards are deliberately conservative so seeded chaos (one or two
    injected failovers, bursty test traffic) reads as weather, not an
    incident: the failover rule needs a ≥3-failover burst inside one
    sampling tick, and the goodput rule needs minutes of baseline plus
    a near-total (80%) collapse before firing.
    """
    return [
        AnomalyRule(
            "fleet_tpot_skew",
            level(store, "dllama_fleet_tpot_skew_ms"),
            direction="high",
            z_threshold=4.0,
            min_abs=5.0,
            rel_frac=1.0,
            min_samples=30,
        ),
        AnomalyRule(
            "fleet_failover_rate",
            slope(store, "dllama_router_failovers_total"),
            direction="high",
            z_threshold=4.0,
            min_abs=3.0,
            min_samples=60,
        ),
        AnomalyRule(
            "fleet_goodput",
            level(store, "dllama_fleet_goodput_tokens_per_s"),
            direction="low",
            z_threshold=4.0,
            rel_frac=0.8,
            min_mean=1.0,
            min_samples=120,
        ),
    ]


# ---------------------------------------------------------------------------
# the scraper/aggregator
# ---------------------------------------------------------------------------

_REPLICA_STATES = ("healthy", "degraded", "draining", "dead")


def _default_fetch(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5.0) as r:
        data: bytes = r.read()
    return data.decode("utf-8", "replace")


class FleetObs:
    """Scrape -> relabel -> aggregate -> monitor; see module docstring.

    ``fetch`` and ``clock`` are injectable so the fleet anomaly path is
    coverable by a deterministic fake-clock test (no live replicas, no
    real time): a fake fetch hands back crafted per-replica scrape text
    and ``sample_once(now)`` drives the monitor tick by tick.
    """

    def __init__(
        self,
        replicas: ReplicaRegistry,
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
        fetch: Callable[[str], str] | None = None,
        clock: Callable[[], float] = time.monotonic,
        interval_s: float | None = None,
        retention_s: float | None = None,
        affinity_rate_fn: Callable[[], float | None] | None = None,
    ) -> None:
        interval, retention, _ = resolve_fleet_obs_knobs(
            interval_s, retention_s
        )
        self.interval_s = interval
        self.replicas = replicas
        self.obs = registry if registry is not None else get_registry()
        self.recorder = recorder if recorder is not None else get_recorder()
        self._fetch = fetch if fetch is not None else _default_fetch
        self._clock = clock
        self._affinity_rate_fn = affinity_rate_fn
        self.store = SeriesStore(
            interval_s=interval,
            retention_s=retention,
            registry=self.obs,
            recorder=self.recorder,
        )
        self.sampler = MetricsSampler(
            self.store, registry=self.obs, clock=clock
        )
        self.monitor = AnomalyMonitor(
            build_fleet_rules(self.store),
            registry=self.obs,
            recorder=self.recorder,
            clock=clock,
        )
        # monitor AFTER the tick's values land in the store (the sampler
        # calls run_refresh_hooks -> the scrape -> flat_values -> record
        # -> on_sample), so every rule reads this tick's aggregates
        self.sampler.on_sample.append(self.monitor.evaluate)
        self.g_goodput = self.obs.gauge(
            "dllama_fleet_goodput_tokens_per_s",
            "Aggregate fleet goodput: sum of every scraped replica's "
            "1-minute SLO-met tokens/s.",
        )
        self.g_replica_goodput = self.obs.gauge(
            "dllama_fleet_replica_goodput_tokens_per_s",
            "Per-replica 1-minute SLO goodput as scraped by the router "
            "(the fleet dashboard's per-replica overlay).",
            labelnames=("replica",),
        )
        self.g_replica_tpot = self.obs.gauge(
            "dllama_fleet_replica_tpot_p50_ms",
            "Per-replica TPOT p50 in ms, computed by the router from "
            "the scraped dllama_tpot_seconds histogram buckets.",
            labelnames=("replica",),
        )
        self.g_tpot_skew = self.obs.gauge(
            "dllama_fleet_tpot_skew_ms",
            "Max minus min per-replica TPOT p50 across the fleet (ms): "
            "the sick-replica spread the fleet_tpot_skew anomaly rule "
            "watches.",
        )
        self.g_affinity_rate = self.obs.gauge(
            "dllama_fleet_affinity_hit_rate",
            "Fraction of routed requests served by their prefix-affinity "
            "target replica (cumulative, from the router's counters).",
        )
        self.g_replicas = self.obs.gauge(
            "dllama_fleet_replicas",
            "Replica count by registry state (healthy / degraded / "
            "draining / dead).",
            labelnames=("state",),
        )
        self.m_scrapes = self.obs.counter(
            "dllama_fleet_scrapes_total",
            "Router scrapes of replica /metrics endpoints by outcome "
            "(ok, error).",
            labelnames=("outcome",),
        )
        # relabeled per-replica sections for the /metrics re-export
        self._sections_lock = make_lock("fleet.obs.sections")
        self._sections: dict[str, str] = {}
        # scrape guard: non-blocking (in-process recursion) + throttled
        self._scrape_lock = threading.Lock()
        self._scrape_last: float | None = None
        self._hook_registered = False

    # -- wiring ------------------------------------------------------------

    def register(self) -> None:
        """Install the scrape as a keyed refresh hook: the router's
        ``/metrics`` handler and the fleet sampler both call
        ``run_refresh_hooks()``, so either keeps the aggregates warm."""
        self.obs.add_refresh_hook("fleet_obs", self._refresh)
        self._hook_registered = True

    def start(self) -> None:
        self.register()
        self.sampler.start()

    def close(self) -> None:
        """Stop the sampler and unhook the scrape (test/bench churn must
        not leak a hook that scrapes dead ports forever)."""
        self.sampler.stop()
        if self._hook_registered:
            self.obs.remove_refresh_hook("fleet_obs")
            self._hook_registered = False

    # -- the scrape --------------------------------------------------------

    def _refresh(self) -> None:
        """Refresh-hook entry: re-entrancy-guarded + throttled. The
        in-process fleet shares ONE registry, so a replica handling our
        scrape GET runs this very hook again — the non-blocking acquire
        turns that inner call into a no-op instead of a recursion."""
        if not self._scrape_lock.acquire(blocking=False):
            return
        try:
            now = self._clock()
            if (
                self._scrape_last is not None
                and now - self._scrape_last < self.interval_s
            ):
                return
            self._scrape_last = now
            self.scrape_once()
        finally:
            self._scrape_lock.release()

    def scrape_once(self) -> dict[str, bool]:
        """Scrape every replica once, rebuild the re-export sections and
        set the fleet aggregate gauges. Returns per-replica success."""
        views = self.replicas.views()
        counts = dict.fromkeys(_REPLICA_STATES, 0)
        for v in views.values():
            counts[v.state] = counts.get(v.state, 0) + 1
        for st, n in counts.items():
            self.g_replicas.labels(state=st).set(float(n))
        per_goodput: dict[str, float] = {}
        per_tpot_ms: dict[str, float] = {}
        ok: dict[str, bool] = {}
        sections: dict[str, str] = {}
        for name in sorted(views):
            url = views[name].base_url
            try:
                text = self._fetch(f"{url}/metrics")
            except (OSError, ValueError) as e:
                ok[name] = False
                self.m_scrapes.labels(outcome="error").inc()
                self.recorder.record(
                    "fleet_scrape_error", replica=name,
                    error=f"{type(e).__name__}: {e}",
                )
                continue
            ok[name] = True
            self.m_scrapes.labels(outcome="ok").inc()
            sections[name] = relabel_prom_text(
                text, name,
                # the in-process fleet's shared registry puts the
                # router's and the fleet's OWN families into every
                # replica scrape; re-exporting those replica-labelled
                # would be recursion in data form
                skip_prefixes=("dllama_router_", "dllama_fleet_"),
            )
            series = parse_prom_text(text)
            for sname, labels, value in series:
                if (
                    sname == GOODPUT_METRIC
                    and labels.get("window") == "1m"
                ):
                    per_goodput[name] = value
            tpot = histogram_quantile(series, "dllama_tpot_seconds", 0.5)
            if tpot is not None:
                per_tpot_ms[name] = tpot * 1000.0
        with self._sections_lock:
            self._sections = sections
        if per_goodput:
            self.g_goodput.set(sum(per_goodput.values()))
        for name, v in per_goodput.items():
            self.g_replica_goodput.labels(replica=name).set(v)
        for name, v in per_tpot_ms.items():
            self.g_replica_tpot.labels(replica=name).set(v)
        if len(per_tpot_ms) >= 2:
            self.g_tpot_skew.set(
                max(per_tpot_ms.values()) - min(per_tpot_ms.values())
            )
        elif per_tpot_ms:
            self.g_tpot_skew.set(0.0)
        if self._affinity_rate_fn is not None:
            rate = self._affinity_rate_fn()
            if rate is not None:
                self.g_affinity_rate.set(rate)
        return ok

    # -- the re-export -----------------------------------------------------

    def render_fleet(self) -> str:
        """The replica-labelled re-export block appended to the router's
        own ``/metrics`` render (values-only lines; HELP/TYPE metadata
        lives on the replicas)."""
        with self._sections_lock:
            sections = dict(self._sections)
        parts = [sections[name] for name in sorted(sections) if sections[name]]
        return "\n".join(parts)
