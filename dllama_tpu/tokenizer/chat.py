"""Chat templating and streaming EOS detection.

Behavioral ports of the reference's ChatTemplateGenerator
(src/tokenizer.cpp:549-637) and EosDetector (src/tokenizer.cpp:639-724).
"""

from __future__ import annotations

import dataclasses
import enum


class ChatTemplateType(enum.IntEnum):
    """(reference: src/tokenizer.hpp:102-108)"""

    UNKNOWN = 0
    LLAMA2 = 1
    LLAMA3 = 2
    DEEP_SEEK3 = 3
    CHATML = 4


# CLI names (reference: parseChatTemplateType, src/app.cpp); the argparse
# choices and every name->type lookup derive from this single map
CHAT_TEMPLATE_NAMES = {
    "llama2": ChatTemplateType.LLAMA2,
    "llama3": ChatTemplateType.LLAMA3,
    "deepSeek3": ChatTemplateType.DEEP_SEEK3,
    "chatml": ChatTemplateType.CHATML,
}


@dataclasses.dataclass
class ChatItem:
    role: str
    message: str


@dataclasses.dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None


def detect_chat_template(chat_template: str) -> ChatTemplateType:
    """Template auto-detection from jinja source content
    (reference: src/tokenizer.cpp:552-565)."""
    if "[INST]" in chat_template:
        return ChatTemplateType.LLAMA2
    if "<|start_header_id|>" in chat_template:
        return ChatTemplateType.LLAMA3
    if "<｜Assistant｜>" in chat_template:
        return ChatTemplateType.DEEP_SEEK3
    if "<|im_start|>" in chat_template:
        return ChatTemplateType.CHATML
    raise ValueError("not supported chat template")


class ChatTemplateGenerator:
    """Renders role messages into a prompt string
    (reference: src/tokenizer.cpp:549-637)."""

    def __init__(
        self,
        type: ChatTemplateType,
        chat_template: str | None,
        eos: str,
    ):
        if type == ChatTemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("the tokenizer does not include chat template")
            self.type = detect_chat_template(chat_template)
        else:
            self.type = type
        self.eos = eos

    def generate(
        self, items: list[ChatItem], append_generation_prompt: bool = True
    ) -> GeneratedChat:
        buf: list[str] = []
        public_prompt_size = 0
        eos = self.eos

        if self.type == ChatTemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append(
                    "[INST] <<SYS>>\n"
                    + items[0].message
                    + "\n<</SYS>>\n\n"
                    + items[1].message
                    + " [/INST]"
                    + eos
                )
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    buf.append(item.message + eos)
                elif item.role == "user":
                    buf.append("[INST] " + item.message + " [/INST]" + eos)
        elif self.type == ChatTemplateType.LLAMA3:
            for item in items:
                buf.append(
                    "<|start_header_id|>"
                    + item.role
                    + "<|end_header_id|>\n\n"
                    + item.message
                    + eos
                )
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == ChatTemplateType.DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for item in items[i:]:
                if item.role == "user":
                    buf.append("<｜User｜>" + item.message)
                elif item.role == "assistant":
                    buf.append("<｜Assistant｜>" + item.message)
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt_size = 8
        elif self.type == ChatTemplateType.CHATML:
            for item in items:
                if item.role == "system":
                    buf.append("<|im_start|>system\n" + item.message + "<|im_end|>\n")
                elif item.role == "user":
                    buf.append("<|im_start|>user\n" + item.message + "<|im_end|>\n")
                elif item.role == "assistant":
                    buf.append(
                        "<|im_start|>assistant\n" + item.message + "<|im_end|>\n"
                    )
                # Quirk kept from the reference (src/tokenizer.cpp:623-624):
                # the generation prompt is appended once per item, inside the
                # loop, not after it.
                if append_generation_prompt:
                    buf.append("<|im_start|>assistant\n")

        content = "".join(buf)
        public_prompt = (
            content[len(content) - public_prompt_size :]
            if public_prompt_size > 0
            else None
        )
        return GeneratedChat(content=content, public_prompt=public_prompt)


class EosResult(enum.IntEnum):
    """(reference: src/tokenizer.hpp:130-134)"""

    MAYBE_EOS = 0
    EOS = 1
    NOT_EOS = 2


class EosDetector:
    """Streaming multi-token stop-string matcher with padding windows
    (reference: src/tokenizer.cpp:639-724).

    ``padding_left`` allows junk before a stop string (e.g. a leading space),
    ``padding_right`` allows trailing bytes after it within the window.
    """

    def __init__(
        self,
        tokens: list[int],
        pieces: list[str],
        padding_left: int = 0,
        padding_right: int = 0,
    ):
        # Unlike the reference (which always passes parallel arrays), the
        # token-id set and the stop-string set are independent here: the API
        # server combines the tokenizer's EOS ids with client-supplied stop
        # strings of any count.
        self.tokens = list(tokens)
        self.pieces = list(pieces)
        self.piece_sizes = [len(p) for p in pieces]
        self.padding_left = padding_left
        self.padding_right = padding_right
        self.buffer = ""
        self.eos_pos = -1

    def is_eos(self, token_id: int) -> bool:
        return token_id in self.tokens

    def append(self, token_id: int, piece: str | None) -> EosResult:
        if piece is not None:
            self.buffer += piece

        if self.is_eos(token_id):
            self.eos_pos = len(self.buffer)
            return EosResult.EOS
        self.eos_pos = -1

        buf_len = len(self.buffer)
        for s, piece_size in zip(self.pieces, self.piece_sizes):
            if buf_len > piece_size + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = buf_len - lo
                if n == 0 or n > piece_size + self.padding_right:
                    continue
                n = min(n, piece_size)
                if self.buffer[lo : lo + n] == s[:n]:
                    if n == piece_size:
                        self.eos_pos = lo
                        self.buffer = self.buffer[:lo]
                        return EosResult.EOS
                    return EosResult.MAYBE_EOS
        return EosResult.NOT_EOS

    def get_delta(self) -> str | None:
        """Printable text accumulated since the last reset, with any matched
        stop string stripped (reference: src/tokenizer.cpp:715-720)."""
        if not self.buffer:
            return None
        if self.eos_pos == 0:
            return None
        return self.buffer

    def reset(self) -> None:
        self.buffer = ""
        self.eos_pos = -1
