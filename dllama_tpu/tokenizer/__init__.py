from .bpe import Tokenizer
from .chat import (
    ChatItem,
    ChatTemplateType,
    ChatTemplateGenerator,
    EosDetector,
    EosResult,
)

__all__ = [
    "Tokenizer",
    "ChatItem",
    "ChatTemplateType",
    "ChatTemplateGenerator",
    "EosDetector",
    "EosResult",
]
