from .bpe import Tokenizer
from .chat import (
    CHAT_TEMPLATE_NAMES,
    ChatItem,
    ChatTemplateType,
    ChatTemplateGenerator,
    EosDetector,
    EosResult,
)

__all__ = [
    "Tokenizer",
    "CHAT_TEMPLATE_NAMES",
    "ChatItem",
    "ChatTemplateType",
    "ChatTemplateGenerator",
    "EosDetector",
    "EosResult",
]
