from .bpe import StreamDecoder, Tokenizer
from .chat import (
    CHAT_TEMPLATE_NAMES,
    ChatItem,
    ChatTemplateType,
    ChatTemplateGenerator,
    EosDetector,
    EosResult,
)

__all__ = [
    "StreamDecoder",
    "Tokenizer",
    "CHAT_TEMPLATE_NAMES",
    "ChatItem",
    "ChatTemplateType",
    "ChatTemplateGenerator",
    "EosDetector",
    "EosResult",
]
