"""Score-based BPE tokenizer over `.t` vocabularies.

Behavioral port of the reference tokenizer (src/tokenizer.cpp:196-390):

* the vocab splits at ``bos_id`` into regular tokens (exact-match lookup)
  and special tokens (prefix scan in id order);
* ``encode`` greedily accumulates bytes until the accumulated span is a
  regular token, then runs the score-maximizing pair-merge loop;
* ``decode`` is a streaming detokenizer: pieces are raw bytes, multi-byte
  UTF-8 sequences may span several tokens, and invalid bytes recover to
  U+FFFD (src/tokenizer.cpp:224-309) — implemented with Python's
  incremental UTF-8 decoder, which has exactly those semantics.

Departure from the reference (an intentional upgrade, same results): the
merge loop keeps the O(n) scan per round but looks pairs up in a dict
instead of bsearch over a sorted array.
"""

from __future__ import annotations

import codecs

from ..formats.tokenizer_file import TokenizerData, read_tokenizer


class Tokenizer:
    """Tokenizer over a `.t` vocabulary (reference: src/tokenizer.hpp:35-70)."""

    def __init__(self, source: str | TokenizerData):
        data = read_tokenizer(source) if isinstance(source, str) else source
        self.data = data
        self.vocab: list[bytes] = data.vocab
        self.scores: list[float] = data.scores
        self.vocab_size = len(data.vocab)
        self.bos_id = data.bos_id
        self.add_bos = data.add_bos
        self.eos_token_ids = list(data.eos_token_ids)
        self.chat_template = data.chat_template
        self.max_token_length = data.max_token_length

        # Regular/special split at bos_id (reference: src/tokenizer.cpp:138-153).
        self.regular_vocab_size = self.bos_id
        # Exact-match index; on duplicate strings keep the first id, matching
        # what a bsearch over a stably-sorted array would most often return.
        self._regular: dict[bytes, int] = {}
        for i in range(self.regular_vocab_size):
            self._regular.setdefault(self.vocab[i], i)
        self._special_ids = list(range(self.regular_vocab_size, self.vocab_size))

        self._decoder = codecs.getincrementaldecoder("utf-8")("replace")
        self._native_index = None  # built lazily on first encode
        self._native_checked = False

    # -- encode ---------------------------------------------------------------

    def _encode_native(
        self, raw: bytes, add_special_tokens: bool, bos_id: int
    ) -> list[int] | None:
        """C++ encode hot loop (native/dllama_native.cpp bpe_encode —
        identical selection semantics, O(n log n) heap over the O(n^2)
        rescan, vocab index built once); None when the native library
        isn't available or the input is un-tokenizable, punting back to
        the Python loop."""
        if not self._native_checked:
            self._native_checked = True
            from ..utils import native

            if native.load_library() is not None:
                import numpy as np

                blob = b"".join(self.vocab)
                offsets = np.zeros(self.vocab_size + 1, dtype=np.int64)
                np.cumsum([len(v) for v in self.vocab], out=offsets[1:])
                self._native_index = native.make_bpe_index(
                    np.frombuffer(blob, dtype=np.uint8),
                    offsets,
                    np.asarray(self.scores, dtype=np.float32),
                    self.regular_vocab_size,
                )
        if self._native_index is None:
            return None
        return self._native_index.encode(raw, bos_id, add_special_tokens)

    def find_regular_token(self, piece: bytes) -> int:
        """Exact regular-vocab lookup (reference: src/tokenizer.cpp:206-210)."""
        return self._regular.get(piece, -1)

    def find_special_token_start_with(self, text: bytes, start: int = 0) -> int:
        """First special token that prefixes ``text[start:]``, scanned in id
        order (reference: src/tokenizer.cpp:196-204). Offset-based to avoid
        copying a tail slice per byte position."""
        for tid in self._special_ids:
            if text.startswith(self.vocab[tid], start):
                return tid
        return -1

    def encode(
        self,
        text: str | bytes,
        is_start: bool = True,
        add_special_tokens: bool = True,
    ) -> list[int]:
        """Encode text to token ids (reference: src/tokenizer.cpp:311-390)."""
        if text is None:
            raise ValueError("input text is None")
        raw = text.encode("utf-8") if isinstance(text, str) else bytes(text)

        use_bos = is_start and self.add_bos and self.bos_id >= 0
        result = self._encode_native(
            raw, add_special_tokens, self.bos_id if use_bos else -1
        )
        if result is not None:
            return result

        tokens: list[int] = []
        if use_bos:
            tokens.append(self.bos_id)

        # Greedy byte accumulation; specials matched by prefix at every byte
        # position — even mid-accumulation, in which case the special is
        # emitted and accumulation continues across it, exactly as the
        # reference does (src/tokenizer.cpp:325-333).
        acc = bytearray()
        i = 0
        n = len(raw)
        while i < n:
            if add_special_tokens:
                sid = self.find_special_token_start_with(raw, i)
                if sid >= 0:
                    tokens.append(sid)
                    i += len(self.vocab[sid])
                    continue
            acc.append(raw[i])
            i += 1
            tid = self.find_regular_token(bytes(acc))
            if tid != -1:
                tokens.append(tid)
                acc.clear()
        if acc:
            raise ValueError(
                f"un-tokenizable trailing bytes (vocab lacks byte fallback?): {bytes(acc)!r}"
            )

        # Score-maximizing pair merge (reference: src/tokenizer.cpp:349-378).
        while True:
            best_score = -1e10
            best_id = -1
            best_idx = -1
            for j in range(len(tokens) - 1):
                merged = self.vocab[tokens[j]] + self.vocab[tokens[j + 1]]
                mid = self._regular.get(merged, -1)
                if mid != -1 and self.scores[mid] > best_score:
                    best_score = self.scores[mid]
                    best_id = mid
                    best_idx = j
            if best_idx == -1:
                break
            tokens[best_idx : best_idx + 2] = [best_id]
        return tokens

    # -- decode ---------------------------------------------------------------

    def is_eos(self, token: int) -> bool:
        return token in self.eos_token_ids

    def reset_decoder(self) -> None:
        """Drop pending partial UTF-8 state (reference: resetDecoder)."""
        self._decoder.reset()

    def _decode_with(self, decoder, token: int) -> str | None:
        """Streaming decode of one token against an explicit incremental
        UTF-8 decoder; shared by the tokenizer's own stream and per-lane
        StreamDecoders (reference: src/tokenizer.cpp:291-309)."""
        if token == self.bos_id:
            return None
        if not 0 <= token < self.vocab_size:
            # the model's vocab is larger than the tokenizer's (the
            # reference would read out of bounds here); fail with context
            raise ValueError(
                f"token {token} outside tokenizer vocab "
                f"({self.vocab_size} entries) — model/tokenizer mismatch?"
            )
        if self.is_eos(token):
            # Flush whatever partial sequence is pending (reference returns the
            # raw pending buffer; we replace the incomplete tail like the
            # recovery path would).
            out = decoder.decode(b"", final=True)
            decoder.reset()
            return out if out else None
        piece = self.vocab[token]
        out = decoder.decode(piece)
        return out if out else None

    def decode(self, token: int) -> str | None:
        """Streaming decode of one token; returns printable text accumulated so
        far or None (reference: src/tokenizer.cpp:291-309)."""
        return self._decode_with(self._decoder, token)

    def stream_decoder(self) -> "StreamDecoder":
        """An INDEPENDENT streaming decoder over this vocab — one per
        serving lane, so concurrent requests don't interleave their UTF-8
        state (the tokenizer's own decode() keeps a single stream, like
        the reference's single-request loop)."""
        return StreamDecoder(self)

    def decode_tokens(self, tokens: list[int]) -> str:
        """Non-streaming convenience: decode a whole sequence. Starts from a
        clean decoder so stale streaming state cannot leak in."""
        self.reset_decoder()
        parts = []
        for t in tokens:
            s = self.decode(t)
            if s:
                parts.append(s)
        tail = self._decoder.decode(b"", final=True)
        self._decoder.reset()
        if tail:
            parts.append(tail)
        return "".join(parts)

    def print_header(self) -> None:
        """Startup info (reference: src/tokenizer.cpp:180-194)."""
        if self.bos_id >= 0:
            print(f"📄 AddBos: {int(self.add_bos)}")
            print(f"📄 BosId: {self.bos_id} ({self.vocab[self.bos_id].decode('utf-8', 'replace')})")
        if self.eos_token_ids:
            eos = " ".join(
                f"{t} ({self.vocab[t].decode('utf-8', 'replace')})"
                for t in self.eos_token_ids
            )
            print(f"📄 EosId: {eos}")
        print(f"📄 RegularVocabSize: {self.regular_vocab_size}")
        print(f"📄 SpecialVocabSize: {self.vocab_size - self.regular_vocab_size}")


class StreamDecoder:
    """Per-lane streaming token decoder: same vocab/EOS rules as the
    owning Tokenizer, independent incremental UTF-8 state."""

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._decoder = codecs.getincrementaldecoder("utf-8")("replace")

    def decode(self, token: int) -> str | None:
        return self._tok._decode_with(self._decoder, token)

    def reset(self) -> None:
        self._decoder.reset()
