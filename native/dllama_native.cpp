// Native data-loader kernels for dllama-tpu.
//
// The TPU-native counterpart of the reference's C++ weight pipeline
// (mmap + per-node slicing + socket streaming, src/llm.cpp:614-669 and
// src/nn/nn-core.cpp:289-322): here the hot host-side work is unpacking
// Q40 blocks (nibble extraction) and transposing tensors into the device
// layout before jax.device_put ships shards over PCIe/ICI. numpy does this
// single-threaded with several materialized intermediates; these kernels do
// it in one multithreaded pass, which is what makes a 40 GB 70B checkpoint
// load in minutes instead of hours.
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 in the
// image). All functions are thread-parallel over the output's leading
// dimension with the same SPLIT_THREADS partitioning idea the reference
// uses (src/nn/nn-quants.hpp:82-86).

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kBlock = 32;          // Q40/Q80 block size
constexpr int kBlockBytes = 18;     // fp16 scale + 16 packed nibble bytes

inline float f16_to_f32(uint16_t h) {
    // scalar IEEE half -> float (no F16C dependency)
    uint32_t sign = (uint32_t)(h >> 15) & 1u;
    uint32_t exp = (uint32_t)(h >> 10) & 0x1Fu;
    uint32_t mant = (uint32_t)h & 0x3FFu;
    uint32_t out;
    if (exp == 0) {
        if (mant == 0) {
            out = sign << 31;
        } else {
            // subnormal: normalize
            exp = 127 - 15 + 1;
            while ((mant & 0x400u) == 0) {
                mant <<= 1;
                exp--;
            }
            mant &= 0x3FFu;
            out = (sign << 31) | (exp << 23) | (mant << 13);
        }
    } else if (exp == 0x1F) {
        out = (sign << 31) | (0xFFu << 23) | (mant << 13);
    } else {
        out = (sign << 31) | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &out, sizeof(f));
    return f;
}

template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
    if (n_threads <= 1 || n < 2) {
        fn(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t chunk = n / n_threads;
    int64_t rest = n % n_threads;
    int64_t start = 0;
    for (int t = 0; t < n_threads; t++) {
        int64_t len = chunk + (t < rest ? 1 : 0);
        if (len == 0) continue;
        threads.emplace_back([=] { fn(start, start + len); });
        start += len;
    }
    for (auto &th : threads) th.join();
}

}  // namespace

extern "C" {

// Unpack packed Q40 rows ([rows, cols] logical, cols % 32 == 0) directly
// into the TRANSPOSED device layout:
//   q_out  int8  [cols, rows]   (contraction axis leading)
//   d_out  float [cols/32, rows]
// raw is rows * cols/32 blocks of 18 bytes, row-major.
void q40_unpack_transposed(const uint8_t *raw, int64_t rows, int64_t cols,
                           int8_t *q_out, float *d_out, int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    // Tile over rows so transpose writes land in contiguous TILE-wide runs
    // (a naive per-element scatter is cache-hostile and no faster than
    // numpy). Each thread owns a range of row tiles.
    constexpr int64_t TILE = 128;
    const int64_t n_tiles = (rows + TILE - 1) / TILE;
    parallel_for(n_tiles, n_threads, [=](int64_t t0, int64_t t1) {
        int8_t tile[kBlock][TILE];
        for (int64_t tr = t0; tr < t1; tr++) {
            const int64_t r0 = tr * TILE;
            const int64_t r1 = r0 + TILE < rows ? r0 + TILE : rows;
            const int64_t width = r1 - r0;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const int64_t col0 = b * kBlock;
                for (int64_t r = r0; r < r1; r++) {
                    const uint8_t *blk =
                        raw + (r * blocks_per_row + b) * kBlockBytes;
                    uint16_t h;
                    std::memcpy(&h, blk, 2);
                    d_out[b * rows + r] = f16_to_f32(h);
                    const uint8_t *qs = blk + 2;
                    const int64_t rr = r - r0;
                    for (int j = 0; j < kBlock / 2; j++) {
                        tile[j][rr] = (int8_t)(qs[j] & 0x0F) - 8;
                        tile[j + kBlock / 2][rr] = (int8_t)(qs[j] >> 4) - 8;
                    }
                }
                for (int j = 0; j < kBlock; j++)
                    std::memcpy(q_out + (col0 + j) * rows + r0, tile[j],
                                (size_t)width);
            }
        }
    });
}

// Dequantize packed Q40 rows to dense f32 in the TRANSPOSED [cols, rows]
// layout the dense loader wants (file is [rows, cols] row-major).
void q40_dequant_transposed(const uint8_t *raw, int64_t rows, int64_t cols,
                            float *out, int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    parallel_for(rows, n_threads, [=](int64_t r0, int64_t r1) {
        for (int64_t r = r0; r < r1; r++) {
            const uint8_t *row = raw + r * blocks_per_row * kBlockBytes;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const uint8_t *blk = row + b * kBlockBytes;
                uint16_t h;
                std::memcpy(&h, blk, 2);
                const float d = f16_to_f32(h);
                const uint8_t *qs = blk + 2;
                const int64_t col0 = b * kBlock;
                for (int j = 0; j < kBlock / 2; j++) {
                    out[(col0 + j) * rows + r] =
                        (float)((int)(qs[j] & 0x0F) - 8) * d;
                    out[(col0 + j + kBlock / 2) * rows + r] =
                        (float)((int)(qs[j] >> 4) - 8) * d;
                }
            }
        }
    });
}

// Dequantize packed Q40 rows to dense f32 in file order [rows, cols]
// (embedding tables and other non-transposed consumers).
void q40_dequant(const uint8_t *raw, int64_t rows, int64_t cols, float *out,
                 int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    parallel_for(rows, n_threads, [=](int64_t r0, int64_t r1) {
        for (int64_t r = r0; r < r1; r++) {
            const uint8_t *row = raw + r * blocks_per_row * kBlockBytes;
            float *orow = out + r * cols;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const uint8_t *blk = row + b * kBlockBytes;
                uint16_t h;
                std::memcpy(&h, blk, 2);
                const float d = f16_to_f32(h);
                const uint8_t *qs = blk + 2;
                float *o = orow + b * kBlock;
                for (int j = 0; j < kBlock / 2; j++) {
                    o[j] = (float)((int)(qs[j] & 0x0F) - 8) * d;
                    o[j + kBlock / 2] = (float)((int)(qs[j] >> 4) - 8) * d;
                }
            }
        }
    });
}

// f32 [rows, cols] -> transposed [cols, rows] (norms stay small; this is
// for the dense path's big matmul weights).
void f32_transpose(const float *in, int64_t rows, int64_t cols, float *out,
                   int n_threads) {
    constexpr int64_t TILE = 64;
    parallel_for((rows + TILE - 1) / TILE, n_threads, [=](int64_t t0, int64_t t1) {
        for (int64_t tr = t0; tr < t1; tr++) {
            const int64_t r0 = tr * TILE;
            const int64_t r1 = r0 + TILE < rows ? r0 + TILE : rows;
            for (int64_t c0 = 0; c0 < cols; c0 += TILE) {
                const int64_t c1 = c0 + TILE < cols ? c0 + TILE : cols;
                for (int64_t r = r0; r < r1; r++)
                    for (int64_t c = c0; c < c1; c++)
                        out[c * rows + r] = in[r * cols + c];
            }
        }
    });
}

// Score-based BPE encode — the host-side hot loop of the tokenizer
// (reference semantics: src/tokenizer.cpp:311-390; Python twin:
// dllama_tpu/tokenizer/bpe.py Tokenizer.encode). The Python/reference
// merge loop rescans all adjacent pairs per round (O(n^2)); this
// implementation reproduces the EXACT same selection rule — highest
// merged-token score, leftmost pair on ties, strictly-greater than the
// -1e10 floor — with a lazy max-heap over a doubly-linked token list
// (O(n log n)): merges never reorder surviving tokens, so "leftmost" is
// a stable per-node order key (the original byte offset of the node's
// first constituent), and stale heap entries are dropped by stamp
// validation.
//
// The vocab index is built ONCE per tokenizer (bpe_index_new) — the
// caller keeps the blob/offsets/scores arrays alive for the handle's
// lifetime. A prepended BOS token participates in the merge phase
// exactly like Python's (its list includes the BOS before merging).

struct BpeIndex {
    const uint8_t *blob;
    const int64_t *offsets;
    const float *scores;
    int64_t vocab_size;
    int64_t regular_size;
    int64_t max_regular_len;
    std::unordered_map<std::string_view, int32_t> regular;

    std::string_view piece(int64_t id) const {
        return std::string_view(
            reinterpret_cast<const char *>(blob) + offsets[id],
            (size_t)(offsets[id + 1] - offsets[id]));
    }
};

void *bpe_index_new(const uint8_t *vocab_blob, const int64_t *offsets,
                    const float *scores, int64_t vocab_size,
                    int64_t regular_size) {
    // a malformed .t header can leave bos_id (= regular split) at -1;
    // returning null lets the Python side fall back to its own loop and
    // raise a catchable error instead of aborting through the C ABI
    if (regular_size < 0 || regular_size > vocab_size || vocab_size < 0)
        return nullptr;
    auto *ix = new BpeIndex{vocab_blob, offsets, scores,
                            vocab_size,  regular_size, 0,
                            {}};
    ix->regular.reserve((size_t)regular_size * 2);
    for (int64_t i = 0; i < regular_size; i++) {
        // first id wins on duplicates (bpe.py builds _regular with
        // setdefault in ascending id order)
        ix->regular.emplace(ix->piece(i), (int32_t)i);
        const int64_t len = offsets[i + 1] - offsets[i];
        if (len > ix->max_regular_len) ix->max_regular_len = len;
    }
    return ix;
}

void bpe_index_free(void *handle) { delete (BpeIndex *)handle; }

// Returns the token count, or -1 when out_cap is too small, or -2 for
// un-tokenizable trailing bytes (the caller falls back to Python, which
// raises the detailed error).
int64_t bpe_encode(void *handle, const uint8_t *text, int64_t text_len,
                   int64_t prepend_bos_id, int add_specials, int32_t *out,
                   int64_t out_cap) {
    const BpeIndex &ix = *(const BpeIndex *)handle;
    const auto piece = [&](int64_t id) { return ix.piece(id); };
    const auto &regular = ix.regular;
    const float *scores = ix.scores;
    const int64_t vocab_size = ix.vocab_size;
    const int64_t regular_size = ix.regular_size;
    const int64_t max_token_len = ix.max_regular_len;

    // 1. greedy byte accumulation with special-token prefix matching at
    //    every byte position (specials scanned in id order)
    std::vector<int32_t> toks;
    toks.reserve((size_t)text_len / 2 + 8);
    if (prepend_bos_id >= 0) toks.push_back((int32_t)prepend_bos_id);
    std::string acc;
    int64_t i = 0;
    const std::string_view text_sv(reinterpret_cast<const char *>(text),
                                   (size_t)text_len);
    while (i < text_len) {
        if (add_specials) {
            int64_t sid = -1;
            for (int64_t s = regular_size; s < vocab_size; s++) {
                std::string_view sp = piece(s);
                if (!sp.empty() &&
                    text_sv.compare((size_t)i, sp.size(), sp) == 0) {
                    sid = s;
                    break;
                }
            }
            if (sid >= 0) {
                toks.push_back((int32_t)sid);
                i += (int64_t)piece(sid).size();
                continue;
            }
        }
        acc.push_back((char)text[i]);
        i++;
        auto it = regular.find(std::string_view(acc));
        if (it != regular.end()) {
            toks.push_back(it->second);
            acc.clear();
        }
    }
    if (!acc.empty()) return -2;

    // 2. score-maximizing pair merge over a linked list + lazy heap
    const int64_t n = (int64_t)toks.size();
    if (n > 1) {
        struct Node {
            int32_t tok;
            int64_t order;  // stable left-to-right key (never reassigned)
            int64_t prev, next;
            uint32_t stamp;  // bumped whenever tok changes / node dies
            bool alive;
        };
        std::vector<Node> nodes((size_t)n);
        for (int64_t j = 0; j < n; j++)
            nodes[(size_t)j] = {toks[(size_t)j], j, j - 1,
                                j + 1 < n ? j + 1 : -1, 0, true};

        struct Cand {
            float score;
            int64_t order;
            int64_t left, right;
            uint32_t lstamp, rstamp;
            int32_t merged;
        };
        struct CandLess {
            bool operator()(const Cand &a, const Cand &b) const {
                if (a.score != b.score) return a.score < b.score;
                return a.order > b.order;  // leftmost wins ties
            }
        };
        std::priority_queue<Cand, std::vector<Cand>, CandLess> heap;
        std::string merged;
        const auto push_cand = [&](int64_t l, int64_t r) {
            const std::string_view a = piece(nodes[(size_t)l].tok);
            const std::string_view b = piece(nodes[(size_t)r].tok);
            if (max_token_len > 0 &&
                (int64_t)(a.size() + b.size()) > max_token_len)
                return;
            merged.assign(a);
            merged.append(b);
            auto it = regular.find(std::string_view(merged));
            if (it == regular.end()) return;
            const float sc = scores[it->second];
            if (!(sc > -1e10f)) return;  // the scan's best_score floor
            heap.push({sc, nodes[(size_t)l].order, l, r,
                       nodes[(size_t)l].stamp, nodes[(size_t)r].stamp,
                       it->second});
        };
        for (int64_t j = 0; j + 1 < n; j++) push_cand(j, j + 1);

        while (!heap.empty()) {
            const Cand c = heap.top();
            heap.pop();
            Node &l = nodes[(size_t)c.left];
            Node &r = nodes[(size_t)c.right];
            if (!l.alive || !r.alive || l.stamp != c.lstamp ||
                r.stamp != c.rstamp || l.next != c.right)
                continue;  // stale entry
            l.tok = c.merged;
            l.stamp++;
            r.alive = false;
            r.stamp++;
            l.next = r.next;
            if (r.next >= 0) nodes[(size_t)r.next].prev = c.left;
            if (l.prev >= 0) push_cand(l.prev, c.left);
            if (l.next >= 0) push_cand(c.left, l.next);
        }

        toks.clear();
        for (int64_t j = 0; j >= 0; j = nodes[(size_t)j].next)
            if (nodes[(size_t)j].alive) toks.push_back(nodes[(size_t)j].tok);
    }

    if ((int64_t)toks.size() > out_cap) return -1;
    std::memcpy(out, toks.data(), toks.size() * sizeof(int32_t));
    return (int64_t)toks.size();
}

int dllama_native_version() { return 3; }

}  // extern "C"
