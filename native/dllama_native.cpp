// Native data-loader kernels for dllama-tpu.
//
// The TPU-native counterpart of the reference's C++ weight pipeline
// (mmap + per-node slicing + socket streaming, src/llm.cpp:614-669 and
// src/nn/nn-core.cpp:289-322): here the hot host-side work is unpacking
// Q40 blocks (nibble extraction) and transposing tensors into the device
// layout before jax.device_put ships shards over PCIe/ICI. numpy does this
// single-threaded with several materialized intermediates; these kernels do
// it in one multithreaded pass, which is what makes a 40 GB 70B checkpoint
// load in minutes instead of hours.
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 in the
// image). All functions are thread-parallel over the output's leading
// dimension with the same SPLIT_THREADS partitioning idea the reference
// uses (src/nn/nn-quants.hpp:82-86).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kBlock = 32;          // Q40/Q80 block size
constexpr int kBlockBytes = 18;     // fp16 scale + 16 packed nibble bytes

inline float f16_to_f32(uint16_t h) {
    // scalar IEEE half -> float (no F16C dependency)
    uint32_t sign = (uint32_t)(h >> 15) & 1u;
    uint32_t exp = (uint32_t)(h >> 10) & 0x1Fu;
    uint32_t mant = (uint32_t)h & 0x3FFu;
    uint32_t out;
    if (exp == 0) {
        if (mant == 0) {
            out = sign << 31;
        } else {
            // subnormal: normalize
            exp = 127 - 15 + 1;
            while ((mant & 0x400u) == 0) {
                mant <<= 1;
                exp--;
            }
            mant &= 0x3FFu;
            out = (sign << 31) | (exp << 23) | (mant << 13);
        }
    } else if (exp == 0x1F) {
        out = (sign << 31) | (0xFFu << 23) | (mant << 13);
    } else {
        out = (sign << 31) | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &out, sizeof(f));
    return f;
}

template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
    if (n_threads <= 1 || n < 2) {
        fn(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t chunk = n / n_threads;
    int64_t rest = n % n_threads;
    int64_t start = 0;
    for (int t = 0; t < n_threads; t++) {
        int64_t len = chunk + (t < rest ? 1 : 0);
        if (len == 0) continue;
        threads.emplace_back([=] { fn(start, start + len); });
        start += len;
    }
    for (auto &th : threads) th.join();
}

}  // namespace

extern "C" {

// Unpack packed Q40 rows ([rows, cols] logical, cols % 32 == 0) directly
// into the TRANSPOSED device layout:
//   q_out  int8  [cols, rows]   (contraction axis leading)
//   d_out  float [cols/32, rows]
// raw is rows * cols/32 blocks of 18 bytes, row-major.
void q40_unpack_transposed(const uint8_t *raw, int64_t rows, int64_t cols,
                           int8_t *q_out, float *d_out, int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    // Tile over rows so transpose writes land in contiguous TILE-wide runs
    // (a naive per-element scatter is cache-hostile and no faster than
    // numpy). Each thread owns a range of row tiles.
    constexpr int64_t TILE = 128;
    const int64_t n_tiles = (rows + TILE - 1) / TILE;
    parallel_for(n_tiles, n_threads, [=](int64_t t0, int64_t t1) {
        int8_t tile[kBlock][TILE];
        for (int64_t tr = t0; tr < t1; tr++) {
            const int64_t r0 = tr * TILE;
            const int64_t r1 = r0 + TILE < rows ? r0 + TILE : rows;
            const int64_t width = r1 - r0;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const int64_t col0 = b * kBlock;
                for (int64_t r = r0; r < r1; r++) {
                    const uint8_t *blk =
                        raw + (r * blocks_per_row + b) * kBlockBytes;
                    uint16_t h;
                    std::memcpy(&h, blk, 2);
                    d_out[b * rows + r] = f16_to_f32(h);
                    const uint8_t *qs = blk + 2;
                    const int64_t rr = r - r0;
                    for (int j = 0; j < kBlock / 2; j++) {
                        tile[j][rr] = (int8_t)(qs[j] & 0x0F) - 8;
                        tile[j + kBlock / 2][rr] = (int8_t)(qs[j] >> 4) - 8;
                    }
                }
                for (int j = 0; j < kBlock; j++)
                    std::memcpy(q_out + (col0 + j) * rows + r0, tile[j],
                                (size_t)width);
            }
        }
    });
}

// Dequantize packed Q40 rows to dense f32 in the TRANSPOSED [cols, rows]
// layout the dense loader wants (file is [rows, cols] row-major).
void q40_dequant_transposed(const uint8_t *raw, int64_t rows, int64_t cols,
                            float *out, int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    parallel_for(rows, n_threads, [=](int64_t r0, int64_t r1) {
        for (int64_t r = r0; r < r1; r++) {
            const uint8_t *row = raw + r * blocks_per_row * kBlockBytes;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const uint8_t *blk = row + b * kBlockBytes;
                uint16_t h;
                std::memcpy(&h, blk, 2);
                const float d = f16_to_f32(h);
                const uint8_t *qs = blk + 2;
                const int64_t col0 = b * kBlock;
                for (int j = 0; j < kBlock / 2; j++) {
                    out[(col0 + j) * rows + r] =
                        (float)((int)(qs[j] & 0x0F) - 8) * d;
                    out[(col0 + j + kBlock / 2) * rows + r] =
                        (float)((int)(qs[j] >> 4) - 8) * d;
                }
            }
        }
    });
}

// Dequantize packed Q40 rows to dense f32 in file order [rows, cols]
// (embedding tables and other non-transposed consumers).
void q40_dequant(const uint8_t *raw, int64_t rows, int64_t cols, float *out,
                 int n_threads) {
    const int64_t blocks_per_row = cols / kBlock;
    parallel_for(rows, n_threads, [=](int64_t r0, int64_t r1) {
        for (int64_t r = r0; r < r1; r++) {
            const uint8_t *row = raw + r * blocks_per_row * kBlockBytes;
            float *orow = out + r * cols;
            for (int64_t b = 0; b < blocks_per_row; b++) {
                const uint8_t *blk = row + b * kBlockBytes;
                uint16_t h;
                std::memcpy(&h, blk, 2);
                const float d = f16_to_f32(h);
                const uint8_t *qs = blk + 2;
                float *o = orow + b * kBlock;
                for (int j = 0; j < kBlock / 2; j++) {
                    o[j] = (float)((int)(qs[j] & 0x0F) - 8) * d;
                    o[j + kBlock / 2] = (float)((int)(qs[j] >> 4) - 8) * d;
                }
            }
        }
    });
}

// f32 [rows, cols] -> transposed [cols, rows] (norms stay small; this is
// for the dense path's big matmul weights).
void f32_transpose(const float *in, int64_t rows, int64_t cols, float *out,
                   int n_threads) {
    constexpr int64_t TILE = 64;
    parallel_for((rows + TILE - 1) / TILE, n_threads, [=](int64_t t0, int64_t t1) {
        for (int64_t tr = t0; tr < t1; tr++) {
            const int64_t r0 = tr * TILE;
            const int64_t r1 = r0 + TILE < rows ? r0 + TILE : rows;
            for (int64_t c0 = 0; c0 < cols; c0 += TILE) {
                const int64_t c1 = c0 + TILE < cols ? c0 + TILE : cols;
                for (int64_t r = r0; r < r1; r++)
                    for (int64_t c = c0; c < c1; c++)
                        out[c * rows + r] = in[r * cols + c];
            }
        }
    });
}

int dllama_native_version() { return 1; }

}  // extern "C"
