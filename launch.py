#!/usr/bin/env python
"""Model downloader / launcher.

Same registry and CLI shape as the reference's launch.py: downloads
prequantized `.m`/`.t` artifacts (multi-part, resumable) from the
distributed-llama HuggingFace repos — the formats are wire-compatible, so
the same artifacts drive this framework — then prints/writes the run
command (TPU flavor: `python -m dllama_tpu ... --tp N`).

    python launch.py <model> [-y] [--tp N]
    python launch.py          # list models
"""

from __future__ import annotations

import os
import socket
import sys
from urllib.request import urlopen


def parts(length: int) -> list[str]:
    return [chr(97 + i // 26) + chr(97 + i % 26) for i in range(length)]


def hf(repo: str, file: str) -> str:
    return f"https://huggingface.co/{repo}/resolve/main/{file}?download=true"


# name -> (model-urls, tokenizer-url, run-mode, extra-args)
# registry mirrors the reference launch.py:17-73
MODELS = {
    "llama3_1_8b_instruct_q40": (
        [hf("b4rtaz/Llama-3_1-8B-Q40-Instruct-Distributed-Llama", "dllama_model_llama3.1_instruct_q40.m")],
        hf("b4rtaz/Llama-3_1-8B-Q40-Instruct-Distributed-Llama", "dllama_tokenizer_llama_3_1.t"),
        "chat", "--max-seq-len 4096",
    ),
    "llama3_1_405b_instruct_q40": (
        [hf("b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama", f"dllama_model_llama31_405b_q40_{s}") for s in parts(56)],
        hf("b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama", "dllama_tokenizer_llama_3_1.t"),
        "chat", "--max-seq-len 4096",
    ),
    "llama3_2_1b_instruct_q40": (
        [hf("b4rtaz/Llama-3_2-1B-Q40-Instruct-Distributed-Llama", "dllama_model_llama3.2-1b-instruct_q40.m")],
        hf("b4rtaz/Llama-3_2-1B-Q40-Instruct-Distributed-Llama", "dllama_tokenizer_llama3_2.t"),
        "chat", "--max-seq-len 4096",
    ),
    "llama3_2_3b_instruct_q40": (
        [hf("b4rtaz/Llama-3_2-3B-Q40-Instruct-Distributed-Llama", "dllama_model_llama3.2-3b-instruct_q40.m")],
        hf("b4rtaz/Llama-3_2-3B-Q40-Instruct-Distributed-Llama", "dllama_tokenizer_llama3_2.t"),
        "chat", "--max-seq-len 4096",
    ),
    "llama3_3_70b_instruct_q40": (
        [hf("b4rtaz/Llama-3_3-70B-Q40-Instruct-Distributed-Llama", f"dllama_model_llama-3.3-70b_q40{s}") for s in parts(11)],
        hf("b4rtaz/Llama-3_3-70B-Q40-Instruct-Distributed-Llama", "dllama_tokenizer_llama-3.3-70b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "deepseek_r1_distill_llama_8b_q40": (
        [hf("b4rtaz/DeepSeek-R1-Distill-Llama-8B-Distributed-Llama", "dllama_model_deepseek-r1-distill-llama-8b_q40.m")],
        hf("b4rtaz/DeepSeek-R1-Distill-Llama-8B-Distributed-Llama", "dllama_tokenizer_deepseek-r1-distill-llama-8b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "qwen3_0.6b_q40": (
        [hf("b4rtaz/Qwen3-0.6B-Q40-Distributed-Llama", "dllama_model_qwen3_0.6b_q40.m")],
        hf("b4rtaz/Qwen3-0.6B-Q40-Distributed-Llama", "dllama_tokenizer_qwen3_0.6b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "qwen3_1.7b_q40": (
        [hf("b4rtaz/Qwen3-1.7B-Q40-Distributed-Llama", "dllama_model_qwen3_1.7b_q40.m")],
        hf("b4rtaz/Qwen3-1.7B-Q40-Distributed-Llama", "dllama_tokenizer_qwen3_1.7b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "qwen3_8b_q40": (
        [hf("b4rtaz/Qwen3-8B-Q40-Distributed-Llama", "dllama_model_qwen3_8b_q40.m")],
        hf("b4rtaz/Qwen3-8B-Q40-Distributed-Llama", "dllama_tokenizer_qwen3_8b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "qwen3_14b_q40": (
        [hf("b4rtaz/Qwen3-14B-Q40-Distributed-Llama", f"dllama_model_qwen3_14b_q40_{s}") for s in parts(2)],
        hf("b4rtaz/Qwen3-14B-Q40-Distributed-Llama", "dllama_tokenizer_qwen3_14b.t"),
        "chat", "--max-seq-len 4096",
    ),
    "qwen3_30b_a3b_q40": (
        [hf("b4rtaz/Qwen3-30B-A3B-Q40-Distributed-Llama", f"dllama_model_qwen3_30b_a3b_{s}") for s in parts(5)],
        hf("b4rtaz/Qwen3-30B-A3B-Q40-Distributed-Llama", "dllama_tokenizer_qwen3_30b_a3b.t"),
        "chat", "--max-seq-len 4096",
    ),
}


def confirm(message: str) -> bool:
    if "-y" in sys.argv:
        return True
    return input(f'❓ {message} ("Y" if yes): ').upper() in ("Y", "YES")


def _download_part(url: str, part_path: str) -> None:
    """One part with byte-range resume: restarts continue from the bytes
    already on disk. A part that is already complete is detected by the
    server's 416 Range-Not-Satisfiable answer and skipped."""
    import urllib.error
    from urllib.request import Request

    for attempt in range(8):
        start = os.path.getsize(part_path) if os.path.isfile(part_path) else 0
        print(f"📄 {url} (attempt: {attempt}, resume at {start >> 20} MB)")
        try:
            req = Request(url)
            if start > 0:
                req.add_header("Range", f"bytes={start}-")
            try:
                response = urlopen(req)
            except urllib.error.HTTPError as e:
                if e.code == 416 and start > 0:
                    print("   part already complete")
                    return
                raise
            with response, open(part_path, "ab" if start else "wb") as f:
                if start > 0 and response.status != 206:
                    # server ignored the Range header: restart the part
                    f.seek(0)
                    f.truncate()
                while True:
                    chunk = response.read(1 << 16)
                    if not chunk:
                        break
                    f.write(chunk)
                    mb = f.tell() >> 20
                    if mb % 100 == 0:
                        print(f"\r📦 {mb} MB", end="", flush=True)
            print()
            return
        except Exception as e:
            print(f"\n⚠️  {e}; retrying")
    raise SystemExit(f"download failed: {url}")


def download_file(urls: list[str], path: str) -> None:
    """Multi-part download; each part resumes independently. Assembly
    renames part 0 and appends+deletes the rest one by one, so peak disk
    use stays ~1x the artifact size."""
    if os.path.isfile(path):
        if not confirm(f"{os.path.basename(path)} already exists, download again?"):
            return
    socket.setdefaulttimeout(30)
    part_paths = [f"{path}.part{i}" for i in range(len(urls))]
    for url, part_path in zip(urls, part_paths):
        _download_part(url, part_path)
    os.replace(part_paths[0], path)
    with open(path, "ab") as out:
        for part_path in part_paths[1:]:
            with open(part_path, "rb") as f:
                while True:
                    chunk = f.read(1 << 22)
                    if not chunk:
                        break
                    out.write(chunk)
            os.remove(part_path)
    print(f"✅ {path}")


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    if not args:
        print("Usage: python launch.py <model> [-y] [--tp N]")
        print()
        print("Available models:")
        for name in MODELS:
            print(f"  {name}")
        sys.exit(1)
    name = args[0]
    if name not in MODELS:
        raise SystemExit(f"unknown model: {name}")
    tp = ""
    if "--tp" in sys.argv:
        tp = f" --tp {sys.argv[sys.argv.index('--tp') + 1]}"

    model_urls, tok_url, mode, extra = MODELS[name]
    os.makedirs("models", exist_ok=True)
    model_path = f"models/dllama_model_{name}.m"
    tok_path = f"models/dllama_tokenizer_{name}.t"
    download_file(model_urls, model_path)
    download_file([tok_url], tok_path)

    cmd = (
        f"python -m dllama_tpu {mode} --model {model_path} "
        f"--tokenizer {tok_path} {extra}{tp}"
    )
    script = f"run_{name}.sh"
    with open(script, "w") as f:
        f.write("#!/bin/sh\n" + cmd + "\n")
    os.chmod(script, 0o755)
    print(f"To run the model, execute: ./{script}")
    print(f"   {cmd}")
    if confirm("Do you want to run the model now?"):
        os.system(cmd)


if __name__ == "__main__":
    main()
