"""Decode-throughput benchmark. Prints ONE JSON line on stdout.

Measures single-stream greedy decode tokens/sec, p50 TTFT (prefill a
128-token prompt + first decode token), and the effective weight-read
bandwidth (weight bytes touched per decode step / step time) on a
BASELINE.json-shaped model, on whatever devices the runtime exposes (the
driver runs this on one real TPU chip).

vs_baseline: fraction of the BASELINE.json north-star bar — 50 decode
tokens/s/chip (the Llama-3.3-70B-on-v5e-8 target; BASELINE.json
"metric"). The metric name carries the preset, so a 1B run scoring >1 is
expected and self-interpreting; the previous denominator (the reference's
2.02 tok/s on RPi hardware) flattered every preset and is gone.

Env knobs: BENCH_PRESET (default llama-8b — the preset closest to the north-star per-chip load), BENCH_STEPS, BENCH_TP,
BENCH_FORMAT, BENCH_SEQ_LEN, BENCH_SKIP_TTFT, BENCH_BATCH (concurrent-lane
metric, default 4; 0 disables — adds one extra compile + 2x steps of
batch-N decode to the run).
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import jax
from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax.numpy as jnp
import numpy as np

NORTH_STAR_TOK_S_PER_CHIP = 50.0  # BASELINE.json: 70B Q40 on v5e-8
BASELINE_DEF = "50 tok/s/chip north star (BASELINE.json 70B-on-v5e-8)"


def weight_bytes_per_token(h, weight_format: str) -> int:
    """HBM bytes of weights a single decode step must read: every matmul
    weight once (MoE: attention weights + the active experts' share).
    Q40 device layout = int8 values + f32 scale per 32 block = 1.125
    B/weight; dense bf16 = 2 B/weight."""
    bpw = 1.125 if weight_format == "q40" else 2.0
    att = h.dim * h.q_dim + 2 * h.dim * h.kv_dim + h.q_dim * h.dim
    ffn = 3 * h.dim * h.ff_dim
    if h.n_experts:
        ffn *= h.n_active_experts  # ragged kernel reads active experts only
    total = (h.n_layers * (att + ffn) + h.dim * h.vocab_size) * bpw
    if h.n_experts:
        total += h.n_layers * h.dim * h.n_experts * 4  # f32 gate
    return int(total)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _device_watchdog(timeout_s: float = 180.0) -> None:
    """The tunneled TPU platform HANGS (rather than erroring) when its
    relay is down; probe it under a timer so the bench emits a result line
    and exits instead of wedging the driver."""
    import threading

    done = threading.Event()
    result: dict = {}

    def probe():
        try:
            import numpy as _np

            import jax.numpy as _jnp

            _ = _np.asarray(_jnp.ones((8, 8)) @ _jnp.ones((8, 8)))
            result["ok"] = True
        except Exception as e:  # real error: report it, don't fake a timeout
            result["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    done.wait(timeout_s)
    if not result.get("ok"):
        if not os.environ.get("BENCH_CPU_FALLBACK"):
            # an in-process platform switch deadlocks (the hung plugin probe
            # holds the backend-init lock), so re-exec cleanly on CPU; the
            # emitted metric is suffixed _cpu_fallback so the record is
            # honest about the hardware it ran on
            print(
                "accelerator unreachable ("
                + result.get("error", "device probe timed out")
                + "); re-exec on CPU fallback",
                file=sys.stderr,
                flush=True,
            )
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["BENCH_CPU_FALLBACK"] = "1"
            # big presets are untenable on CPU (the q40 fallback dequantizes
            # per call); the tiny preset keeps the fallback line cheap, and
            # the whole config is forced consistent (an inherited BENCH_TP
            # would fail the 1-device mesh; inherited steps would overrun
            # the shortened cache)
            env["BENCH_PRESET"] = "tiny"
            env["BENCH_SEQ_LEN"] = "64"
            env["BENCH_STEPS"] = "16"
            env["BENCH_TP"] = "1"
            env["BENCH_SKIP_TTFT"] = "1"  # keep the CPU fallback line cheap
            os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
        print(
            json.dumps(
                {
                    "metric": "decode_tok_s_per_chip_unavailable",
                    "value": 0.0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": 0.0,
                    "error": result.get(
                        "error", "accelerator unreachable (device probe timed out)"
                    ),
                }
            )
        )
        os._exit(0)


def main() -> None:
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dllama_tpu.models import forward, init_kv_cache
    from dllama_tpu.models.synthetic import make_header, random_params
    from dllama_tpu.parallel import cache_specs, make_mesh

    _device_watchdog()

    preset = os.environ.get("BENCH_PRESET", "llama-8b")
    steps = int(os.environ.get("BENCH_STEPS", "64"))
    tp = int(os.environ.get("BENCH_TP", "0")) or 1
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "1024"))
    weight_format = os.environ.get("BENCH_FORMAT", "q40")

    h = make_header(preset, max_seq_len=seq_len)
    log(f"bench: {preset}, tp={tp}, steps={steps}, seq_len={h.seq_len}, "
        f"format={weight_format}, devices={jax.devices()}")

    mesh = make_mesh(tp=tp)
    t0 = time.perf_counter()
    params = random_params(
        h, dtype=jnp.bfloat16, mesh=mesh, weight_format=weight_format,
        # fused qkv/w13 launches, like the engine's q40 default
        fuse=tp if weight_format == "q40" else 0,
    )
    cache = init_kv_cache(h, batch_size=1, dtype=jnp.bfloat16)
    cspecs = cache_specs(h)
    cache = {
        k: jax.device_put(v, NamedSharding(mesh, cspecs[k])) for k, v in cache.items()
    }
    jax.block_until_ready(jax.tree.leaves(params)[0])
    log(f"params built in {time.perf_counter() - t0:.1f}s")

    from jax import lax

    # On-device multi-step decode (the engine's decode_block structure):
    # the sample->feed loop runs under fori_loop, one host dispatch per
    # block of `steps` tokens.
    @partial(jax.jit, donate_argnums=(2,), static_argnums=(3,))
    def decode_block(params, token, cache, n, pos0):
        # batch-generic (jit specializes per token/cache shape): the same
        # program serves the single-stream and the concurrent-lane metric
        def body(i, carry):
            tok, cache = carry
            logits, cache = forward(params, h, tok, pos0 + i, cache, mesh=mesh)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return nxt[:, None], cache
        return lax.fori_loop(0, n, body, (token, cache))

    token_sharding = NamedSharding(mesh, P(None, None))
    tok = jax.device_put(jnp.asarray([[1]], dtype=jnp.int32), token_sharding)

    # warmup / compile (np.asarray: full sync — block_until_ready returns
    # early on the tunneled axon platform)
    t0 = time.perf_counter()
    tok_out, cache = decode_block(params, tok, cache, steps, jnp.int32(0))
    _ = np.asarray(tok_out)
    log(f"compile+first block: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    tok_out, cache = decode_block(params, tok_out, cache, steps, jnp.int32(steps))
    # np.asarray (not block_until_ready): on the tunneled axon platform
    # block_until_ready returns before the remote computation finishes
    _ = np.asarray(tok_out)
    dt = time.perf_counter() - t0
    tok_s = steps / dt
    per_chip = tok_s / tp
    w_bytes = weight_bytes_per_token(h, weight_format)
    weight_gbs = w_bytes * tok_s / tp / 1e9  # per-chip weight-read bandwidth
    log(f"{steps} decode steps in {dt:.2f}s -> {tok_s:.2f} tok/s "
        f"({per_chip:.2f}/chip, ~{weight_gbs:.0f} GB/s weight reads/chip)")

    # p50 TTFT: prefill a 128-token prompt + first greedy token, one
    # compiled program per shape (BASELINE.json names p50 TTFT as part of
    # the headline metric)
    ttft_p50 = None
    if not os.environ.get("BENCH_SKIP_TTFT"):
        prompt_len = min(128, h.seq_len // 2)

        @partial(jax.jit, donate_argnums=(2,))
        def prefill_first(params, tokens, cache, pos):
            logits, cache = forward(params, h, tokens, pos, cache, mesh=mesh)
            return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), cache

        prompt = jax.device_put(
            jnp.ones((1, prompt_len), jnp.int32), token_sharding
        )
        samples = []
        for i in range(5):
            t0 = time.perf_counter()
            first_tok, cache = prefill_first(params, prompt, cache, jnp.int32(0))
            _ = np.asarray(first_tok)
            samples.append((time.perf_counter() - t0) * 1000)
        ttft_p50 = float(np.median(samples[1:]))  # drop the compile run
        log(f"TTFT (prefill {prompt_len} + 1 token): p50 {ttft_p50:.1f} ms "
            f"(samples: {[f'{s:.0f}' for s in samples]})")

    # concurrent lanes: aggregate decode throughput with BENCH_BATCH
    # independent streams in one program (the continuous-batching surface
    # the reference lacks; also exercises the m>1 kernel paths at scale)
    lanes_tok_s = None
    n_lanes = int(os.environ.get("BENCH_BATCH", "4"))
    if n_lanes > 1 and not os.environ.get("BENCH_CPU_FALLBACK"):
        del cache
        cache_l = init_kv_cache(h, batch_size=n_lanes, dtype=jnp.bfloat16)
        cache_l = {
            k: jax.device_put(v, NamedSharding(mesh, cspecs[k]))
            for k, v in cache_l.items()
        }

        tok_l = jax.device_put(
            jnp.ones((n_lanes, 1), jnp.int32), token_sharding
        )
        tok_l, cache_l = decode_block(
            params, tok_l, cache_l, steps, jnp.int32(0)
        )
        _ = np.asarray(tok_l)  # compile + warmup
        t0 = time.perf_counter()
        tok_l, cache_l = decode_block(
            params, tok_l, cache_l, steps, jnp.int32(steps)
        )
        _ = np.asarray(tok_l)
        dt_l = time.perf_counter() - t0
        lanes_tok_s = n_lanes * steps / dt_l / tp
        log(f"{n_lanes}-lane decode: {lanes_tok_s:.2f} aggregate tok/s/chip "
            f"({lanes_tok_s / per_chip:.2f}x single-stream)")

    result = {
        "metric": (
            f"decode_tok_s_per_chip_{preset.replace('-', '_')}_{weight_format}"
            + ("_cpu_fallback" if os.environ.get("BENCH_CPU_FALLBACK") else "")
        ),
        "value": round(per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / NORTH_STAR_TOK_S_PER_CHIP, 3),
        "baseline_def": BASELINE_DEF,
        "weight_gbs_per_chip": round(weight_gbs, 1),
    }
    if ttft_p50 is not None:
        result["ttft_ms_p50"] = round(ttft_p50, 1)
    if lanes_tok_s is not None:
        result[f"lanes{n_lanes}_tok_s_per_chip"] = round(lanes_tok_s, 2)
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit an honest record instead of a bare crash
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "bench_error",
                    "value": 0.0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(1)  # record printed, but CI/validation must still see red
